"""LLMEngine end-to-end: continuous batching over the paged pool must be
token-identical (greedy) to sequential Generator.generate, including under
preemption from a deliberately starved page pool; plus request lifecycle —
deadline shedding, cancellation, streaming, eos (serving/engine.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config, Generator
from paddle_tpu.serving import LLMEngine, Request, SequenceStatus


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _prompts(model, lengths, seed=0):
    rng = np.random.RandomState(seed)
    v = model.config.vocab_size
    return [rng.randint(0, v, (n,)).tolist() for n in lengths]


def _reference_tokens(model, prompt, n, max_len=64):
    gen = Generator(model, max_len=max_len)
    out = gen.generate(paddle.to_tensor(np.asarray(prompt)[None],
                                        dtype="int64"),
                       max_new_tokens=n, temperature=0.0).numpy()
    return out[0, len(prompt):].tolist()


def test_engine_matches_sequential_generator_8_mixed_requests(tiny_model):
    """The ISSUE acceptance bar: >= 8 concurrent mixed-length requests,
    greedy outputs token-identical to one-at-a-time Generator.generate."""
    lengths = [3, 5, 6, 7, 9, 11, 12, 15]
    prompts = _prompts(tiny_model, lengths)
    eng = LLMEngine(tiny_model, max_len=64, page_size=4,
                    batch_buckets=(1, 2, 4, 8))
    rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    outs = eng.run(max_steps=200)
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].finish_reason == "length"
        assert outs[rid].token_ids == _reference_tokens(tiny_model, p, 5), \
            f"{rid} diverged from the sequential engine"
    snap = eng.metrics_snapshot()
    assert snap["finished_requests"] == 8
    assert snap["tokens_generated"] == 8 * 5
    assert snap["page_utilization"] == 0.0          # all pages returned
    eng.pool.check_invariants()


def test_preemption_requeue_is_token_identical(tiny_model):
    """A pool too small for the offered load must trigger preemption with
    requeue (recompute mode) — and the preempted request's greedy tokens
    must still match the sequential engine exactly."""
    prompts = _prompts(tiny_model, [6, 7, 9, 11], seed=1)
    # each request needs up to ceil((11+8)/4) = 5 pages; 8 usable pages
    # cannot hold four requests at once
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    batch_buckets=(1, 2, 4))
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    outs = eng.run(max_steps=400)
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].token_ids == \
            _reference_tokens(tiny_model, p, 8, max_len=64)
    snap = eng.metrics_snapshot()
    assert snap["preemptions"] >= 1, \
        "the starved pool must have exercised preemption"
    assert any(outs[r].num_preemptions > 0 for r in rids)
    # requeued prefills: more prefill launches than requests
    assert snap["prefills"] > len(rids)
    eng.pool.check_invariants()
    assert eng.pool.free_pages == eng.pool.capacity


def test_deadline_load_shedding(tiny_model):
    """A waiting request whose deadline passes before admission is shed;
    running requests are never shed."""
    clock = [0.0]
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    batch_buckets=(1,), max_prefills_per_step=1,
                    now_fn=lambda: clock[0])
    r_run = eng.add_request([1, 2, 3], max_new_tokens=6, deadline_s=100.0)
    r_shed = eng.add_request([4, 5, 6], max_new_tokens=6, deadline_s=0.5)
    eng.step()                       # admits r_run (batch bucket is 1)
    clock[0] = 1.0                   # r_shed's deadline passes in queue
    eng.step()
    outs = eng.outputs()
    assert outs[r_shed].status == "shed"
    assert outs[r_shed].finish_reason == "shed"
    assert outs[r_shed].token_ids == []
    assert outs[r_run].status in ("running", "finished")
    eng.run(max_steps=100)
    assert eng.outputs()[r_run].status == "finished"
    assert eng.metrics_snapshot()["shed_requests"] == 1


def test_preempted_in_flight_request_is_never_shed(tiny_model):
    """The deadline is a waiting-before-START SLO: a request that already
    streamed tokens and then got preempted back into the queue must NOT
    be shed when its deadline lapses — it resumes and finishes."""
    clock = [0.0]
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=6,
                    batch_buckets=(1, 2), now_fn=lambda: clock[0])
    prompts = _prompts(tiny_model, [6, 6], seed=9)
    rids = [eng.add_request(p, max_new_tokens=8, deadline_s=0.5)
            for p in prompts]
    eng.step()                       # both admitted (2+2 of 5 pages)
    clock[0] = 1.0                   # every deadline now lapsed
    outs = eng.run(max_steps=400)
    snap = eng.metrics_snapshot()
    assert snap["preemptions"] >= 1, "pool of 5 pages must preempt"
    assert snap["shed_requests"] == 0
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].token_ids == \
            _reference_tokens(tiny_model, p, 8, max_len=64)


def test_fresh_preemption_surfaced_once_in_step_outputs(tiny_model):
    """A preemption shows up in that step's touched outputs (status
    'waiting', num_preemptions bumped) and is not re-reported on later
    steps while the sequence sits in the queue."""
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=6,
                    batch_buckets=(1, 2))
    for p in _prompts(tiny_model, [6, 6], seed=9):
        eng.add_request(p, max_new_tokens=8)
    preempt_reports = []
    while eng.has_unfinished():
        for out in eng.step():
            if out.status == "waiting" and out.num_preemptions > 0:
                preempt_reports.append(out.request_id)
    assert eng.metrics_snapshot()["preemptions"] == len(preempt_reports), \
        "each preemption must be surfaced exactly once"


def test_release_frees_retained_outputs(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    rid = eng.add_request([1, 2, 3], max_new_tokens=2)
    with pytest.raises(ValueError, match="still"):
        eng.release(rid)             # not resolved yet
    eng.run(max_steps=50)
    out = eng.release(rid)
    assert out.finished and len(out.token_ids) == 2
    assert rid not in eng.outputs()
    with pytest.raises(KeyError):
        eng.release(rid)


def test_cancellation_running_and_waiting(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4,
                    batch_buckets=(1,), max_prefills_per_step=1)
    r1 = eng.add_request([1, 2, 3], max_new_tokens=10)
    r2 = eng.add_request([4, 5, 6], max_new_tokens=10)
    eng.step()                       # r1 running (1 slot), r2 waiting
    assert eng.cancel(r1)            # cancel mid-flight: frees its pages
    assert eng.cancel(r2)            # cancel while queued
    outs = eng.outputs()
    assert outs[r1].status == "cancelled"
    assert len(outs[r1].token_ids) >= 1      # streamed tokens survive
    assert outs[r2].status == "cancelled" and outs[r2].token_ids == []
    assert not eng.has_unfinished()
    assert eng.pool.free_pages == eng.pool.capacity
    assert not eng.cancel(r1)        # already resolved
    assert eng.metrics_snapshot()["cancelled_requests"] == 2


def test_incremental_streaming_and_eos(tiny_model):
    """stream_cb sees every token in order; eos stops a request early and
    the engine reports finish_reason='eos'."""
    # discover what greedy emits, then set eos to its 3rd token
    prompt = _prompts(tiny_model, [5], seed=3)[0]
    ref = _reference_tokens(tiny_model, prompt, 6)
    eos = ref[2]
    events = []
    eng = LLMEngine(tiny_model, max_len=32, page_size=4,
                    stream_cb=lambda rid, tok, fin: events.append(
                        (rid, tok, fin)))
    rid = eng.add_request(prompt, max_new_tokens=6, eos_token_id=eos)
    outs = eng.run(max_steps=100)
    assert outs[rid].finish_reason == "eos"
    assert outs[rid].token_ids == ref[:3]    # eos token included, then stop
    streamed = [t for r, t, f in events if r == rid]
    assert streamed == ref[:3]
    assert events[-1][2] is True             # final event marks finished


def test_request_dataclass_and_validation(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    rid = eng.add_request(Request(prompt_token_ids=[1, 2],
                                  max_new_tokens=2, request_id="mine"))
    assert rid == "mine"
    with pytest.raises(KeyError):
        eng.add_request([1], request_id="mine")
    with pytest.raises(ValueError):
        eng.add_request([])
    with pytest.raises(ValueError):
        eng.add_request([1, 2, 3], max_new_tokens=30)   # 33 > max_len 32
    with pytest.raises(ValueError):
        eng.add_request([1], max_new_tokens=0)
    eng.run(max_steps=100)
    assert eng.outputs()["mine"].finished


def test_oversized_request_rejected_up_front(tiny_model):
    """A request that could never fit the pool is rejected at add time —
    not discovered via an unserviceable preemption loop later."""
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=4)
    with pytest.raises(ValueError, match="pages"):
        eng.add_request(list(range(1, 17)), max_new_tokens=8)  # 6 > 3 pages


def test_mixed_temperature_batch_greedy_rows_stay_exact(tiny_model):
    """Sampling rows (temp>0) ride the same decode launch as greedy rows
    without perturbing the greedy rows' tokens."""
    prompts = _prompts(tiny_model, [4, 6], seed=5)
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, seed=11)
    r_greedy = eng.add_request(prompts[0], max_new_tokens=4)
    r_sample = eng.add_request(prompts[1], max_new_tokens=4,
                               temperature=0.9)
    outs = eng.run(max_steps=100)
    assert outs[r_greedy].token_ids == \
        _reference_tokens(tiny_model, prompts[0], 4)
    assert len(outs[r_sample].token_ids) == 4
    v = tiny_model.config.vocab_size
    assert all(0 <= t < v for t in outs[r_sample].token_ids)


def test_sequence_status_enum_round_trip():
    assert SequenceStatus.FINISHED.value == "finished"
    assert SequenceStatus("waiting") is SequenceStatus.WAITING


def test_admission_watermark_hysteresis():
    """Once admission halts above the HIGH watermark it stays halted
    until utilization recovers below LOW — no admit/preempt thrash right
    at the high line (scheduler-level, no model needed)."""
    from paddle_tpu.serving import (PagedKVPool, Scheduler, SchedulerConfig,
                                    Sequence)
    pool = PagedKVPool(1, 1, 8, num_pages=11, page_size=4,
                       high_watermark=0.25, low_watermark=0.05)
    sched = Scheduler(pool, SchedulerConfig(batch_buckets=(8,),
                                            max_prefills_per_step=8),
                      max_pages_per_seq=4)

    def _seq(i, tokens=4):          # 1 page each (of 10 usable)
        return Sequence(seq_id=f"s{i}", prompt_ids=[1] * tokens,
                        max_new_tokens=1, arrival=float(i))

    for i in range(5):
        sched.add(_seq(i))
    admitted = sched.admit()
    # s0 (0.1), s1 (0.2); admitting s2 would cross 0.25 -> halt, paused
    assert [s.seq_id for s in admitted] == ["s0", "s1"]
    assert sched._admission_paused
    # drop to 0.1 utilization: between LOW and HIGH — still paused
    sched.finish(admitted[0])
    assert sched.admit() == []
    # drop to 0.0 < LOW: admission resumes (until the high line again)
    sched.finish(admitted[1])
    resumed = sched.admit()
    assert [s.seq_id for s in resumed] == ["s2", "s3"]
    assert sched._admission_paused   # s4 re-tripped the high line


def test_tokens_per_s_is_windowed_not_lifetime():
    """The exported rate reflects the trailing window: it reads zero
    across an idle gap and recovers instantly when traffic resumes —
    not a lifetime average decaying toward zero."""
    from paddle_tpu.serving import ServingMetrics

    class _SchedStub:
        running, waiting = [], []

        def queue_depth(self):
            return 0

    class _PoolStub:
        utilization = 0.0

    clock = [0.0]
    m = ServingMetrics(now_fn=lambda: clock[0])
    m.tokens_generated.inc(100)
    clock[0] = 1.0
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(100.0)
    clock[0] = 1000.0                # a long idle gap
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(0.0), \
        "idle engine must read ~0, not a decayed lifetime average"
    m.tokens_generated.inc(100)      # traffic resumes at full speed
    clock[0] = 1001.0
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(100.0)
