"""LLMEngine end-to-end: continuous batching over the paged pool must be
token-identical (greedy) to sequential Generator.generate, including under
preemption from a deliberately starved page pool; plus request lifecycle —
deadline shedding, cancellation, streaming, eos (serving/engine.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config, Generator
from paddle_tpu.serving import LLMEngine, Request, SequenceStatus


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _prompts(model, lengths, seed=0):
    rng = np.random.RandomState(seed)
    v = model.config.vocab_size
    return [rng.randint(0, v, (n,)).tolist() for n in lengths]


def _reference_tokens(model, prompt, n, max_len=64):
    gen = Generator(model, max_len=max_len)
    out = gen.generate(paddle.to_tensor(np.asarray(prompt)[None],
                                        dtype="int64"),
                       max_new_tokens=n, temperature=0.0).numpy()
    return out[0, len(prompt):].tolist()


def test_engine_matches_sequential_generator_8_mixed_requests(tiny_model):
    """The ISSUE acceptance bar: >= 8 concurrent mixed-length requests,
    greedy outputs token-identical to one-at-a-time Generator.generate."""
    lengths = [3, 5, 6, 7, 9, 11, 12, 15]
    prompts = _prompts(tiny_model, lengths)
    eng = LLMEngine(tiny_model, max_len=64, page_size=4,
                    batch_buckets=(1, 2, 4, 8))
    rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    outs = eng.run(max_steps=200)
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].finish_reason == "length"
        assert outs[rid].token_ids == _reference_tokens(tiny_model, p, 5), \
            f"{rid} diverged from the sequential engine"
    snap = eng.metrics_snapshot()
    assert snap["finished_requests"] == 8
    assert snap["tokens_generated"] == 8 * 5
    assert snap["page_utilization"] == 0.0          # all pages returned
    eng.pool.check_invariants()


def test_preemption_requeue_is_token_identical(tiny_model):
    """A pool too small for the offered load must trigger preemption with
    requeue (recompute mode) — and the preempted request's greedy tokens
    must still match the sequential engine exactly."""
    prompts = _prompts(tiny_model, [6, 7, 9, 11], seed=1)
    # each request needs up to ceil((11+8)/4) = 5 pages; 8 usable pages
    # cannot hold four requests at once
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    batch_buckets=(1, 2, 4))
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    outs = eng.run(max_steps=400)
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].token_ids == \
            _reference_tokens(tiny_model, p, 8, max_len=64)
    snap = eng.metrics_snapshot()
    assert snap["preemptions"] >= 1, \
        "the starved pool must have exercised preemption"
    assert any(outs[r].num_preemptions > 0 for r in rids)
    # requeued prefills: more prefill launches than requests
    assert snap["prefills"] > len(rids)
    eng.pool.check_invariants()
    assert eng.pool.free_pages == eng.pool.capacity


def test_deadline_load_shedding(tiny_model):
    """A waiting request whose deadline passes before admission is shed;
    running requests are never shed."""
    clock = [0.0]
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    batch_buckets=(1,), max_prefills_per_step=1,
                    now_fn=lambda: clock[0])
    r_run = eng.add_request([1, 2, 3], max_new_tokens=6, deadline_s=100.0)
    r_shed = eng.add_request([4, 5, 6], max_new_tokens=6, deadline_s=0.5)
    eng.step()                       # admits r_run (batch bucket is 1)
    clock[0] = 1.0                   # r_shed's deadline passes in queue
    eng.step()
    outs = eng.outputs()
    assert outs[r_shed].status == "shed"
    assert outs[r_shed].finish_reason == "shed"
    assert outs[r_shed].token_ids == []
    assert outs[r_run].status in ("running", "finished")
    eng.run(max_steps=100)
    assert eng.outputs()[r_run].status == "finished"
    assert eng.metrics_snapshot()["shed_requests"] == 1


def test_preempted_in_flight_request_is_never_shed(tiny_model):
    """The deadline is a waiting-before-START SLO: a request that already
    streamed tokens and then got preempted back into the queue must NOT
    be shed when its deadline lapses — it resumes and finishes."""
    clock = [0.0]
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=6,
                    batch_buckets=(1, 2), now_fn=lambda: clock[0])
    prompts = _prompts(tiny_model, [6, 6], seed=9)
    rids = [eng.add_request(p, max_new_tokens=8, deadline_s=0.5)
            for p in prompts]
    eng.step()                       # both admitted (2+2 of 5 pages)
    clock[0] = 1.0                   # every deadline now lapsed
    outs = eng.run(max_steps=400)
    snap = eng.metrics_snapshot()
    assert snap["preemptions"] >= 1, "pool of 5 pages must preempt"
    assert snap["shed_requests"] == 0
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].token_ids == \
            _reference_tokens(tiny_model, p, 8, max_len=64)


def test_fresh_preemption_surfaced_once_in_step_outputs(tiny_model):
    """A preemption shows up in that step's touched outputs (status
    'waiting', num_preemptions bumped) and is not re-reported on later
    steps while the sequence sits in the queue."""
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=6,
                    batch_buckets=(1, 2))
    for p in _prompts(tiny_model, [6, 6], seed=9):
        eng.add_request(p, max_new_tokens=8)
    preempt_reports = []
    while eng.has_unfinished():
        for out in eng.step():
            if out.status == "waiting" and out.num_preemptions > 0:
                preempt_reports.append(out.request_id)
    assert eng.metrics_snapshot()["preemptions"] == len(preempt_reports), \
        "each preemption must be surfaced exactly once"


def test_release_frees_retained_outputs(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    rid = eng.add_request([1, 2, 3], max_new_tokens=2)
    with pytest.raises(ValueError, match="still"):
        eng.release(rid)             # not resolved yet
    eng.run(max_steps=50)
    out = eng.release(rid)
    assert out.finished and len(out.token_ids) == 2
    assert rid not in eng.outputs()
    with pytest.raises(KeyError):
        eng.release(rid)


def test_cancellation_running_and_waiting(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4,
                    batch_buckets=(1,), max_prefills_per_step=1)
    r1 = eng.add_request([1, 2, 3], max_new_tokens=10)
    r2 = eng.add_request([4, 5, 6], max_new_tokens=10)
    eng.step()                       # r1 running (1 slot), r2 waiting
    assert eng.cancel(r1)            # cancel mid-flight: frees its pages
    assert eng.cancel(r2)            # cancel while queued
    outs = eng.outputs()
    assert outs[r1].status == "cancelled"
    assert len(outs[r1].token_ids) >= 1      # streamed tokens survive
    assert outs[r2].status == "cancelled" and outs[r2].token_ids == []
    assert not eng.has_unfinished()
    assert eng.pool.free_pages == eng.pool.capacity
    assert not eng.cancel(r1)        # already resolved
    assert eng.metrics_snapshot()["cancelled_requests"] == 2


def test_incremental_streaming_and_eos(tiny_model):
    """stream_cb sees every token in order; eos stops a request early and
    the engine reports finish_reason='eos'."""
    # discover what greedy emits, then set eos to its 3rd token
    prompt = _prompts(tiny_model, [5], seed=3)[0]
    ref = _reference_tokens(tiny_model, prompt, 6)
    eos = ref[2]
    events = []
    eng = LLMEngine(tiny_model, max_len=32, page_size=4,
                    stream_cb=lambda rid, tok, fin: events.append(
                        (rid, tok, fin)))
    rid = eng.add_request(prompt, max_new_tokens=6, eos_token_id=eos)
    outs = eng.run(max_steps=100)
    assert outs[rid].finish_reason == "eos"
    assert outs[rid].token_ids == ref[:3]    # eos token included, then stop
    streamed = [t for r, t, f in events if r == rid]
    assert streamed == ref[:3]
    assert events[-1][2] is True             # final event marks finished


def test_request_dataclass_and_validation(tiny_model):
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    rid = eng.add_request(Request(prompt_token_ids=[1, 2],
                                  max_new_tokens=2, request_id="mine"))
    assert rid == "mine"
    with pytest.raises(KeyError):
        eng.add_request([1], request_id="mine")
    with pytest.raises(ValueError):
        eng.add_request([])
    with pytest.raises(ValueError):
        eng.add_request([1, 2, 3], max_new_tokens=30)   # 33 > max_len 32
    with pytest.raises(ValueError):
        eng.add_request([1], max_new_tokens=0)
    eng.run(max_steps=100)
    assert eng.outputs()["mine"].finished


def test_oversized_request_rejected_up_front(tiny_model):
    """A request that could never fit the pool is rejected at add time —
    not discovered via an unserviceable preemption loop later."""
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=4)
    with pytest.raises(ValueError, match="pages"):
        eng.add_request(list(range(1, 17)), max_new_tokens=8)  # 6 > 3 pages


def test_mixed_temperature_batch_greedy_rows_stay_exact(tiny_model):
    """Sampling rows (temp>0) ride the same decode launch as greedy rows
    without perturbing the greedy rows' tokens."""
    prompts = _prompts(tiny_model, [4, 6], seed=5)
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, seed=11)
    r_greedy = eng.add_request(prompts[0], max_new_tokens=4)
    r_sample = eng.add_request(prompts[1], max_new_tokens=4,
                               temperature=0.9)
    outs = eng.run(max_steps=100)
    assert outs[r_greedy].token_ids == \
        _reference_tokens(tiny_model, prompts[0], 4)
    assert len(outs[r_sample].token_ids) == 4
    v = tiny_model.config.vocab_size
    assert all(0 <= t < v for t in outs[r_sample].token_ids)


def test_sequence_status_enum_round_trip():
    assert SequenceStatus.FINISHED.value == "finished"
    assert SequenceStatus("waiting") is SequenceStatus.WAITING


def test_admission_watermark_hysteresis():
    """Once admission halts above the HIGH watermark it stays halted
    until utilization recovers below LOW — no admit/preempt thrash right
    at the high line (scheduler-level, no model needed)."""
    from paddle_tpu.serving import (PagedKVPool, Scheduler, SchedulerConfig,
                                    Sequence)
    pool = PagedKVPool(1, 1, 8, num_pages=11, page_size=4,
                       high_watermark=0.25, low_watermark=0.05)
    sched = Scheduler(pool, SchedulerConfig(batch_buckets=(8,),
                                            max_prefills_per_step=8),
                      max_pages_per_seq=4)

    def _seq(i, tokens=4):          # 1 page each (of 10 usable)
        return Sequence(seq_id=f"s{i}", prompt_ids=[1] * tokens,
                        max_new_tokens=1, arrival=float(i))

    for i in range(5):
        sched.add(_seq(i))
    admitted = sched.admit()
    # s0 (0.1), s1 (0.2); admitting s2 would cross 0.25 -> halt, paused
    assert [s.seq_id for s in admitted] == ["s0", "s1"]
    assert sched._admission_paused
    # drop to 0.1 utilization: between LOW and HIGH — still paused
    sched.finish(admitted[0])
    assert sched.admit() == []
    # drop to 0.0 < LOW: admission resumes (until the high line again)
    sched.finish(admitted[1])
    resumed = sched.admit()
    assert [s.seq_id for s in resumed] == ["s2", "s3"]
    assert sched._admission_paused   # s4 re-tripped the high line


def test_tokens_per_s_is_windowed_not_lifetime():
    """The exported rate reflects the trailing window: it reads zero
    across an idle gap and recovers instantly when traffic resumes —
    not a lifetime average decaying toward zero."""
    from paddle_tpu.serving import ServingMetrics

    class _SchedStub:
        running, waiting = [], []

        def queue_depth(self):
            return 0

    class _PoolStub:
        utilization = 0.0

    clock = [0.0]
    m = ServingMetrics(now_fn=lambda: clock[0])
    m.tokens_generated.inc(100)
    clock[0] = 1.0
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(100.0)
    clock[0] = 1000.0                # a long idle gap
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(0.0), \
        "idle engine must read ~0, not a decayed lifetime average"
    m.tokens_generated.inc(100)      # traffic resumes at full speed
    clock[0] = 1001.0
    m.record_step(_SchedStub(), _PoolStub())
    assert m.tokens_per_s.value == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------

def test_chunked_prefill_never_stalls_decodes(tiny_model):
    """A long prompt admitted alongside active decodes is committed in
    chunks across steps — and EVERY running decode row makes one token
    of progress on EVERY one of those steps (the budget reserves q_block
    tokens per row before granting chunk budget)."""
    prompts = _prompts(tiny_model, [3, 4], seed=21)
    eng = LLMEngine(tiny_model, max_len=64, page_size=4, max_num_seqs=4,
                    chunk_size=4, max_prefills_per_step=1)
    rs = [eng.add_request(p, max_new_tokens=20) for p in prompts]
    eng.step(); eng.step()                   # both decoding
    long_p = _prompts(tiny_model, [24], seed=22)[0]
    rl = eng.add_request(long_p, max_new_tokens=4)
    chunk_steps = 0
    while eng._seqs[rl].cached_len < len(long_p):
        before = [len(eng._seqs[r].tokens) for r in rs]
        eng.step()
        chunk_steps += 1
        for b, r in zip(before, rs):
            if eng._seqs[r].status == SequenceStatus.RUNNING:
                assert len(eng._seqs[r].tokens) == b + 1, (
                    "decode row stalled while the long prompt chunked in")
        assert chunk_steps < 50
    assert chunk_steps >= 3, "24-token prompt over chunk_size=4 must chunk"
    outs = eng.run(max_steps=300)
    assert outs[rl].token_ids == _reference_tokens(tiny_model, long_p, 4)
    for r, p in zip(rs, prompts):
        assert outs[r].token_ids == _reference_tokens(tiny_model, p, 20)
    assert eng.metrics_snapshot()["prefill_chunks"] >= 3


def test_chunk_boundary_tokens_identical_to_whole_prompt_prefill(tiny_model):
    """Same executable shape (pinned step_token_budget), different chunk
    boundaries: generated tokens must be IDENTICAL — the ragged step
    computes each token's K/V and logits independently of chunking."""
    prompt = _prompts(tiny_model, [27], seed=23)[0]

    def run(chunk):
        eng = LLMEngine(tiny_model, max_len=64, page_size=4,
                        max_num_seqs=4, chunk_size=chunk, q_block=4,
                        step_token_budget=48)
        rid = eng.add_request(prompt, max_new_tokens=6)
        return eng.run(max_steps=200)[rid].token_ids

    whole = run(32)                          # prompt in ONE chunk
    assert whole == run(4)                   # 7 chunks
    assert whole == run(9)                   # ragged, non-page-aligned
    assert whole == _reference_tokens(tiny_model, prompt, 6)


# ---------------------------------------------------------------------------
# prefix caching + copy-on-write
# ---------------------------------------------------------------------------

def test_prefix_sharing_page_accounting_gate(tiny_model):
    """N sequences over a common prefix allocate <= prefix_pages +
    N*tail_pages physical pages, shared_page_fraction reports the save,
    and every output stays token-identical to the sequential engine."""
    ps = 4
    prefix = _prompts(tiny_model, [16], seed=31)[0]   # 4 full pages
    tails = _prompts(tiny_model, [3, 2, 3], seed=32)
    eng = LLMEngine(tiny_model, max_len=64, page_size=ps, max_num_seqs=4,
                    chunk_size=32)
    donor = eng.add_request(prefix, max_new_tokens=14)  # stays running
    eng.step(); eng.step()                   # donor prompt registered
    rids = [eng.add_request(prefix + t, max_new_tokens=4) for t in tails]
    eng.step()
    snap = eng.metrics_snapshot()
    assert snap["prefix_cache_hits"] == len(tails)
    prefix_pages = len(prefix) // ps
    n = len(tails)
    # per child: tokens beyond the shared prefix (tail + 4 new), plus the
    # donor's own tail growth — bound every sequence's exclusive pages
    child_tail_pages = max(
        eng.pool.pages_for(len(prefix) + len(t) + 4) - prefix_pages
        for t in tails)
    donor_tail_pages = eng.pool.pages_for(len(prefix) + 14) - prefix_pages
    bound = prefix_pages + n * child_tail_pages + donor_tail_pages
    assert eng.pool.used_pages <= bound, (
        f"{eng.pool.used_pages} physical pages > prefix+N*tail bound "
        f"{bound} — prefix sharing is not sharing")
    assert eng.pool.logical_pages - eng.pool.used_pages >= \
        (n - 0) * prefix_pages - n, "children must map the donor's pages"
    assert snap["shared_page_fraction"] > 0.3
    eng.pool.check_invariants()
    outs = eng.run(max_steps=300)
    assert outs[donor].token_ids == _reference_tokens(
        tiny_model, prefix, 14)
    for rid, t in zip(rids, tails):
        assert outs[rid].token_ids == _reference_tokens(
            tiny_model, prefix + t, 4), "forked sequence diverged"

    # admitted-sequences-per-byte: the same wave WITHOUT sharing peaks
    # strictly higher in physical pages
    eng0 = LLMEngine(tiny_model, max_len=64, page_size=ps, max_num_seqs=4,
                     chunk_size=32, prefix_caching=False)
    eng0.add_request(prefix, max_new_tokens=14)
    eng0.step(); eng0.step()
    for t in tails:
        eng0.add_request(prefix + t, max_new_tokens=4)
    eng0.step()
    assert eng0.metrics_snapshot()["prefix_cache_hits"] == 0
    assert eng0.pool.used_pages > eng.pool.used_pages + (n - 1) * \
        prefix_pages - n, "no-sharing engine should pay ~N x prefix pages"
    assert eng0.pool.shared_page_fraction == 0.0
    eng0.run(max_steps=300)


def test_identical_prompt_cow_divergence_on_shared_tail_page(tiny_model):
    """An identical prompt forks even the partially-filled tail page;
    its first append (re-computing the last prompt token for logits)
    copy-on-writes that page — and both the donor's and the fork's
    greedy tokens stay exactly the sequential engine's, before and after
    the post-fork divergence."""
    P = _prompts(tiny_model, [18], seed=33)[0]   # ps=8: tail page holds 2
    eng = LLMEngine(tiny_model, max_len=64, page_size=8, max_num_seqs=4,
                    chunk_size=32)
    donor = eng.add_request(P, max_new_tokens=10)
    eng.step()
    fork = eng.add_request(P, max_new_tokens=5)
    eng.step()
    snap = eng.metrics_snapshot()
    assert snap["prefix_cache_hits"] == 1
    assert snap["cow_copies"] >= 1, \
        "the shared tail page must be duplicated before the fork's append"
    eng.pool.check_invariants()
    outs = eng.run(max_steps=300)
    assert outs[donor].token_ids == _reference_tokens(tiny_model, P, 10)
    assert outs[fork].token_ids == _reference_tokens(tiny_model, P, 5)
    assert eng.pool.free_pages == eng.pool.capacity


def test_preemption_with_prefix_forks_is_token_identical(tiny_model):
    """A pool too small for the forked load must preempt — and every
    sequence (donor, forks, preempted-and-requeued) still reproduces the
    sequential engine's greedy tokens exactly."""
    prefix = _prompts(tiny_model, [12], seed=34)[0]
    tails = _prompts(tiny_model, [2, 3], seed=35)
    prompts = [prefix] + [prefix + t for t in tails]
    # high_watermark=1.0: admit the whole forked load up front so decode
    # growth, not admission control, is what hits the wall
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    max_num_seqs=3, chunk_size=16, high_watermark=1.0)
    donor = eng.add_request(prompts[0], max_new_tokens=8)
    eng.step()
    rids = [donor] + [eng.add_request(p, max_new_tokens=8)
                      for p in prompts[1:]]
    outs = eng.run(max_steps=500)
    snap = eng.metrics_snapshot()
    assert snap["prefix_cache_hits"] >= 1, "forks must have happened"
    assert snap["preemptions"] >= 1, "the starved pool must preempt"
    for rid, p in zip(rids, prompts):
        assert outs[rid].status == "finished"
        assert outs[rid].token_ids == \
            _reference_tokens(tiny_model, p, 8, max_len=64), \
            f"{rid} diverged under preemption + prefix forks"
    eng.pool.check_invariants()
    assert eng.pool.free_pages == eng.pool.capacity


# ---------------------------------------------------------------------------
# oversize rejection (regression: the old bucketed engine could raise
# bucket_for ValueError mid-step(), killing the serving loop)
# ---------------------------------------------------------------------------

def test_oversize_rejected_with_structured_error_and_finalized_output(
        tiny_model):
    from paddle_tpu.serving import RequestRejected
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    ok = eng.add_request([1, 2, 3], max_new_tokens=4)
    with pytest.raises(RequestRejected) as ei:
        eng.add_request(list(range(1, 20)), max_new_tokens=20,
                        request_id="too-big")      # 39 > max_len 32
    err = ei.value
    assert isinstance(err, ValueError)             # legacy callers catch it
    assert err.request_id == "too-big"
    assert err.reason == "rejected_oversize"
    assert err.needed_pages is not None and err.limit is not None
    # finalize-with-reason: polling clients see a terminal state
    out = eng.outputs()["too-big"]
    assert out.status == "aborted" and out.finished
    assert out.finish_reason == "rejected_oversize"
    assert eng.metrics_snapshot()["rejected_requests"] == 1
    # the serving loop was never poisoned: the valid request completes
    outs = eng.run(max_steps=100)
    assert outs[ok].status == "finished"
    assert eng.release("too-big").status == "aborted"


def test_oversize_against_pool_pages_rejected_same_way(tiny_model):
    """The pool-capacity variant (prompt fits max_len, pages don't) gets
    the same structured rejection instead of dying in the scheduler."""
    from paddle_tpu.serving import RequestRejected
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=4)
    with pytest.raises(RequestRejected, match="pages"):
        eng.add_request(list(range(1, 17)), max_new_tokens=8)
    rid = next(iter(eng.outputs()))
    assert eng.outputs()[rid].finish_reason == "rejected_oversize"
    assert not eng.has_unfinished()                # loop is unaffected


def test_reused_request_id_never_forks_a_different_prompt(tiny_model):
    """A released request_id can be reused for a DIFFERENT prompt; stale
    prefix-cache entries naming that id must fail re-validation instead
    of forking the new prompt's pages under the old prompt's chain."""
    A, B = _prompts(tiny_model, [12, 12], seed=41)
    assert A != B
    eng = LLMEngine(tiny_model, max_len=64, page_size=4, max_num_seqs=4,
                    chunk_size=32)
    eng.add_request(A, max_new_tokens=2, request_id="x")
    eng.run(max_steps=100)
    eng.release("x")                         # "x"'s chains are now stale
    eng.add_request(B, max_new_tokens=12, request_id="x")
    eng.step(); eng.step()                   # B committed under id "x"
    victim = eng.add_request(A, max_new_tokens=4)
    outs = eng.run(max_steps=200)
    assert outs[victim].token_ids == _reference_tokens(tiny_model, A, 4), \
        "stale chain forked the WRONG prompt's pages"
    assert outs["x"].token_ids == _reference_tokens(tiny_model, B, 12)
    eng.pool.check_invariants()
