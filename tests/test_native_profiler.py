"""Native runtime (flags/profiler/allocator/workqueue) + profiler API.

Mirrors the reference's C++ unit tests (test/cpp/) + python profiler tests
(test/legacy_test/test_profiler.py) at the Python binding surface.
"""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import native as nv

nv.ensure_loaded()

needs_native = pytest.mark.skipif(not nv.AVAILABLE,
                                  reason="native runtime not built")


@needs_native
def test_flags_mirror_to_native():
    paddle.set_flags({"check_nan_inf": True})
    assert nv.flags.get("check_nan_inf") in ("True", "true", "1")
    paddle.set_flags({"check_nan_inf": False})
    assert paddle.get_flags("check_nan_inf")["FLAGS_check_nan_inf"] is False


@needs_native
def test_allocator_cache_and_stats():
    nv.mem_release_cached()
    base_reserved = nv.mem_reserved()
    b = nv.HostBuffer(1 << 20)
    arr = b.as_numpy(np.float32, (256, 1024))
    arr[:] = 3.0
    assert nv.mem_allocated() >= (1 << 20)
    b.free()
    assert nv.mem_reserved() >= base_reserved + (1 << 20)  # cached
    b2 = nv.HostBuffer(1 << 20)  # reuse from cache, no growth
    assert nv.mem_reserved() == nv.mem_reserved()
    b2.free()
    nv.mem_release_cached()


@needs_native
def test_workqueue_dependencies():
    wq = nv.WorkQueue(4)
    order = []
    lock = threading.Lock()

    def mk(tag):
        def f():
            with lock:
                order.append(tag)
        return f

    a = wq.submit(mk("a"))
    b = wq.submit(mk("b"), deps=[a])
    c = wq.submit(mk("c"), deps=[b])
    wq.wait_all()
    wq.close()
    assert order == ["a", "b", "c"]


@needs_native
def test_native_collate_matches_stack():
    wq = nv.WorkQueue(4)
    srcs = [np.random.randn(32, 32).astype(np.float32) for _ in range(8)]
    dst = np.empty((8, 32, 32), np.float32)
    wq.collate(dst, srcs)
    np.testing.assert_array_equal(dst, np.stack(srcs))
    wq.close()


@needs_native
def test_dataloader_native_fast_path():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.full((64, 64), i, np.float32), np.int64(i)

        def __len__(self):
            return 32

    dl = DataLoader(DS(), batch_size=16)  # 16*16KB > native threshold
    xb, yb = next(iter(dl))
    assert list(xb.shape) == [16, 64, 64]
    np.testing.assert_allclose(xb.numpy()[3], 3.0)


@needs_native
def test_profiler_records_ops_and_exports(tmp_path):
    from paddle_tpu.profiler import Profiler, RecordEvent, ProfilerTarget

    with Profiler(targets=[ProfilerTarget.CPU]) as prof:
        with RecordEvent("user_span"):
            x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
            y = paddle.matmul(x, x)
            _ = paddle.tanh(y).numpy()
        prof.step()
    stats = prof.summary(time_unit="us")
    assert any("matmul" in k for k in stats)
    path = prof.export_chrome_tracing(str(tmp_path))
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "user_span" in names
    assert any("matmul" in n for n in names)


@needs_native
def test_profiler_scheduler_gates_recording():
    from paddle_tpu.profiler import Profiler, ProfilerTarget, make_scheduler

    nv.prof_clear()
    sched = make_scheduler(closed=1, ready=0, record=1, repeat=2)
    prof = Profiler(targets=[ProfilerTarget.CPU], scheduler=sched)
    prof.start()           # step 0: closed
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    _ = paddle.matmul(x, x)
    n_closed = sum(1 for e in nv.prof_export() if e[4] == 1)
    prof.step()            # step 1: record
    _ = paddle.matmul(x, x)
    prof.stop()
    n_after = sum(1 for e in nv.prof_export() if e[4] == 1)
    assert n_closed == 0
    assert n_after >= 1


def test_protobuf_export_and_enums(tmp_path):
    """export_protobuf / load_profiler_result roundtrip (reference:
    profiler.py:280, utils.py:161; schema proto/profiler_result.proto)
    plus SortedKeys-driven summary."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.profiler as profiler

    prof = profiler.Profiler(
        on_trace_ready=profiler.export_protobuf(str(tmp_path)))
    with prof:
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(2):
            paddle.matmul(x, x)
    pbs = list(tmp_path.glob("*.pb"))
    assert len(pbs) == 1
    events = profiler.load_profiler_result(str(pbs[0]))
    assert any(e[0] == "matmul" for e in events)
    stats = prof.summary(sorted_by=profiler.SortedKeys.CPUAvg)
    assert "matmul" in stats
    assert profiler.SummaryView.OperatorView.value == 5


def test_protobuf_roundtrip_events_exact(tmp_path):
    """ISSUE-12 satellite: export_protobuf / load_profiler_result is a
    LOSSLESS round-trip — events-in == events-out, tuple order
    preserved. Uses a stub profiler (the handler only needs .events()),
    so the gate runs with or without the native recorder."""
    import paddle_tpu.profiler as profiler

    events = [
        ("matmul", 1, 100, 50, 1),
        ("user_span", 2, 120, 30, 2),
        ("matmul", 1, 200, 40, 1),       # duplicate name, later start
        ("compile:TrainStep", 1, 10, 990, 2),
        ("serving.queue_depth=3.000", 3, 250, 0, 3),
    ]

    class _StubProf:
        def events(self):
            return list(events)

    path_holder = {}
    handler = profiler.export_protobuf(str(tmp_path), worker_name="t")

    # the handler returns the written path
    path_holder["p"] = handler(_StubProf())
    assert path_holder["p"].endswith("t.pb")
    loaded = profiler.load_profiler_result(path_holder["p"])
    assert loaded == events, "round-trip must preserve every tuple " \
        "and their order"


def test_summary_renders_min_column(capsys, monkeypatch):
    """ISSUE-12 satellite: ``Profiler.summary`` aggregates min_ns but
    the rendered table used to drop the Min column — header and rows
    must both carry it now, and the returned stats keep min_ns."""
    import paddle_tpu.profiler as profiler

    fake = [("op_a", 1, 0, 4_000_000, 1),    # 4 ms
            ("op_a", 1, 10, 1_000_000, 1),   # 1 ms  -> min
            ("op_b", 1, 20, 2_000_000, 1)]
    monkeypatch.setattr(profiler._nv, "prof_export", lambda: list(fake))
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    stats = prof.summary(time_unit="ms")
    out = capsys.readouterr().out
    header = out.splitlines()[0]
    assert "Min" in header and "Max" in header
    # op_a row: calls=2 total=5ms avg=2.5 max=4 min=1
    row_a = next(line for line in out.splitlines() if line.startswith("op_a"))
    cols = row_a.split()
    assert cols[-1] == "1.000" and cols[-2] == "4.000", row_a
    assert stats["op_a"]["min_ns"] == 1_000_000
    # sorted_by="min" orders ascending by min_ns
    stats_min = prof.summary(sorted_by=profiler.SortedKeys.CPUMin)
    assert list(stats_min)[0] == "op_a"
