"""Program/Executor static-graph surface (reference: python/paddle/static/
— Program base/framework.py:5940, Executor base/executor.py:812,
static.data static/input.py:30). The classic paddle 1.x workflow: build
under program_guard, Executor.run with feed/fetch, minimize-based
training, save/load of program parameters."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    # fresh default program for the next test
    static.program.set_default_main_program(static.Program())


def test_build_and_run_forward(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        y = paddle.tanh(lin(x))
    assert not paddle.in_dynamic_mode()
    assert isinstance(y, static.Variable)

    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    # oracle through the same layer in dygraph
    paddle.disable_static()
    ref = paddle.tanh(lin(paddle.to_tensor(xv))).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    assert out.shape == (5, 3)


def test_missing_feed_raises(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    with pytest.raises(ValueError, match="missing feeds"):
        static.Executor().run(main, feed={}, fetch_list=[y])


def test_static_training_minimize(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main, static.default_startup_program()):
        x = static.data("x", [None, 4], "float32")
        label = static.data("label", [None, 1], "float32")
        lin = paddle.nn.Linear(4, 1)
        pred = lin(x)
        loss = ((pred - label) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((16, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    yv = xv @ w
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xv, "label": yv},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_program_state_and_save_load(static_mode, tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 2)
        y = lin(x)
    params = main.parameters()
    assert len(params) == 2  # weight + bias
    prefix = str(tmp_path / "prog")
    static.save(main, prefix)

    # perturb, reload, confirm restoration
    orig = lin.weight.numpy().copy()
    lin.weight._inplace_update(lin.weight._data * 0 + 7.0)
    static.load(main, prefix)
    np.testing.assert_allclose(lin.weight.numpy(), orig, rtol=1e-6)


def test_scope_and_places(static_mode):
    s = static.Scope()
    with static.scope_guard(s):
        assert static.global_scope() is s
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = x + 1.0
        static.Executor().run(main, feed={"x": np.zeros(2, np.float32)},
                              fetch_list=[y])
        assert s.find_var("x") is not None
        np.testing.assert_allclose(s.find_var("x").get_tensor(),
                                   np.zeros(2))
    places = static.cpu_places()
    assert len(places) == 1


def test_dynamic_mode_untouched_after_disable(static_mode):
    paddle.disable_static()
    t = paddle.to_tensor([1.0, 2.0])
    assert float((t * 2).sum().numpy()) == 6.0
    assert paddle.in_dynamic_mode()


def test_append_backward_fetch_grads(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = paddle.mean(lin(x) ** 2)
        pairs = static.append_backward(loss)
    assert pairs and all(gv.name.endswith("@GRAD") for _, gv in pairs)
    xv = np.random.default_rng(1).standard_normal((4, 3)).astype(np.float32)
    outs = static.Executor().run(main, feed={"x": xv},
                                 fetch_list=[loss] + [g for _, g in pairs])
    # numpy oracle: d(mean((xW+b)^2)) = 2/N * x^T (xW+b), sum for b
    w = pairs[0][0].numpy() if pairs[0][0].numpy().shape == (3, 1) \
        else pairs[1][0].numpy()
    b = [p for p, _ in pairs if p.numpy().shape != (3, 1)][0].numpy()
    y = xv @ w + b
    gw = (2 / y.size) * xv.T @ y
    gb = (2 / y.size) * y.sum(0)
    got = {tuple(p.numpy().shape): g for (p, _), g in zip(pairs, outs[1:])}
    np.testing.assert_allclose(got[(3, 1)], gw, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got[(1,)], gb, rtol=1e-4, atol=1e-5)
    # a second run returns the SAME grads (no cross-run accumulation)
    outs2 = static.Executor().run(main, feed={"x": xv},
                                  fetch_list=[g for _, g in pairs])
    got2 = {tuple(p.numpy().shape): g
            for (p, _), g in zip(pairs, outs2)}
    np.testing.assert_allclose(got2[(3, 1)], gw, rtol=1e-4, atol=1e-5)


def test_gradients_wrt_input(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = paddle.sum(x * x)
        (gx,) = static.gradients([y], [x])
    xv = np.array([[1., 2.], [3., 4.]], np.float32)
    (g,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)
    # fetch by name works too
    (g2,) = static.Executor().run(main, feed={"x": xv},
                                  fetch_list=["x@GRAD"])
    np.testing.assert_allclose(g2, 2 * xv, rtol=1e-6)


def test_py_func_and_print(static_mode, capsys):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        out_decl = static.data("out_decl", [2, 2], "float32")
        y = static.py_func(lambda t: paddle.to_tensor(t.numpy() * 3.0),
                           x, out_decl)
        z = static.Print(y, message="dbg")
    # out_decl was a shape declaration — py_func unregisters it as a feed
    assert "out_decl" not in main._feeds
    xv = np.ones((2, 2), np.float32)
    (out,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(out, 3 * xv)
    assert "dbg" in capsys.readouterr().out
    with static.name_scope("block"):
        pass
    with pytest.raises(NotImplementedError):
        static.py_func(lambda t: t, x, out_decl, backward_func=lambda g: g)


def test_compiled_program_and_build_strategy(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    bs = static.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    cp = static.CompiledProgram(main, build_strategy=bs)
    cp = cp.with_data_parallel(loss_name=None)
    xv = np.ones((3, 2), np.float32)
    (out,) = static.Executor().run(cp, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, 2 * xv)
    assert "fuse_elewise_add_act_ops" in repr(bs)


def test_exponential_moving_average():
    # dygraph-style params (the EMA utility is backend-agnostic here)
    p = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    p.stop_gradient = False
    ema = static.ExponentialMovingAverage(0.5, parameter_list=[p])
    ema.update()                      # shadow = p = [1, 2]
    p._inplace_update(p._data * 0 + np.array([3.0, 6.0], np.float32))
    ema.update()                      # shadow = .5*[1,2] + .5*[3,6] = [2,4]
    with ema.apply():
        np.testing.assert_allclose(p.numpy(), [2.0, 4.0])
    np.testing.assert_allclose(p.numpy(), [3.0, 6.0])  # restored
    with ema.apply(need_restore=False):
        pass
    np.testing.assert_allclose(p.numpy(), [2.0, 4.0])


def test_weight_norm_param_attr_and_ipu_stubs():
    attr = static.WeightNormParamAttr(dim=0, name="w")
    assert attr.dim == 0 and isinstance(attr, static.ParamAttr)
    s = static.IpuStrategy()
    s.set_graph_config(num_ipus=1)
    with pytest.raises(RuntimeError, match="IPU backend"):
        static.IpuCompiledProgram(None)
    with pytest.raises(RuntimeError, match="IPU backend"):
        static.ipu_shard_guard(0)
