"""Program/Executor static-graph surface (reference: python/paddle/static/
— Program base/framework.py:5940, Executor base/executor.py:812,
static.data static/input.py:30). The classic paddle 1.x workflow: build
under program_guard, Executor.run with feed/fetch, minimize-based
training, save/load of program parameters."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    # fresh default program for the next test
    static.program.set_default_main_program(static.Program())


def test_build_and_run_forward(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        y = paddle.tanh(lin(x))
    assert not paddle.in_dynamic_mode()
    assert isinstance(y, static.Variable)

    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    # oracle through the same layer in dygraph
    paddle.disable_static()
    ref = paddle.tanh(lin(paddle.to_tensor(xv))).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    assert out.shape == (5, 3)


def test_missing_feed_raises(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    with pytest.raises(ValueError, match="missing feeds"):
        static.Executor().run(main, feed={}, fetch_list=[y])


def test_static_training_minimize(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main, static.default_startup_program()):
        x = static.data("x", [None, 4], "float32")
        label = static.data("label", [None, 1], "float32")
        lin = paddle.nn.Linear(4, 1)
        pred = lin(x)
        loss = ((pred - label) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((16, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    yv = xv @ w
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xv, "label": yv},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_program_state_and_save_load(static_mode, tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 2)
        y = lin(x)
    params = main.parameters()
    assert len(params) == 2  # weight + bias
    prefix = str(tmp_path / "prog")
    static.save(main, prefix)

    # perturb, reload, confirm restoration
    orig = lin.weight.numpy().copy()
    lin.weight._inplace_update(lin.weight._data * 0 + 7.0)
    static.load(main, prefix)
    np.testing.assert_allclose(lin.weight.numpy(), orig, rtol=1e-6)


def test_scope_and_places(static_mode):
    s = static.Scope()
    with static.scope_guard(s):
        assert static.global_scope() is s
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = x + 1.0
        static.Executor().run(main, feed={"x": np.zeros(2, np.float32)},
                              fetch_list=[y])
        assert s.find_var("x") is not None
        np.testing.assert_allclose(s.find_var("x").get_tensor(),
                                   np.zeros(2))
    places = static.cpu_places()
    assert len(places) == 1


def test_dynamic_mode_untouched_after_disable(static_mode):
    paddle.disable_static()
    t = paddle.to_tensor([1.0, 2.0])
    assert float((t * 2).sum().numpy()) == 6.0
    assert paddle.in_dynamic_mode()
