"""Program/Executor static-graph surface (reference: python/paddle/static/
— Program base/framework.py:5940, Executor base/executor.py:812,
static.data static/input.py:30). The classic paddle 1.x workflow: build
under program_guard, Executor.run with feed/fetch, minimize-based
training, save/load of program parameters."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    # fresh default program for the next test
    static.program.set_default_main_program(static.Program())


def test_build_and_run_forward(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        y = paddle.tanh(lin(x))
    assert not paddle.in_dynamic_mode()
    assert isinstance(y, static.Variable)

    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    # oracle through the same layer in dygraph
    paddle.disable_static()
    ref = paddle.tanh(lin(paddle.to_tensor(xv))).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    assert out.shape == (5, 3)


def test_missing_feed_raises(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    with pytest.raises(ValueError, match="missing feeds"):
        static.Executor().run(main, feed={}, fetch_list=[y])


def test_static_training_minimize(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main, static.default_startup_program()):
        x = static.data("x", [None, 4], "float32")
        label = static.data("label", [None, 1], "float32")
        lin = paddle.nn.Linear(4, 1)
        pred = lin(x)
        loss = ((pred - label) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((16, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    yv = xv @ w
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xv, "label": yv},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_program_state_and_save_load(static_mode, tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 2)
        y = lin(x)
    params = main.parameters()
    assert len(params) == 2  # weight + bias
    prefix = str(tmp_path / "prog")
    static.save(main, prefix)

    # perturb, reload, confirm restoration
    orig = lin.weight.numpy().copy()
    lin.weight._inplace_update(lin.weight._data * 0 + 7.0)
    static.load(main, prefix)
    np.testing.assert_allclose(lin.weight.numpy(), orig, rtol=1e-6)


def test_scope_and_places(static_mode):
    s = static.Scope()
    with static.scope_guard(s):
        assert static.global_scope() is s
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = x + 1.0
        static.Executor().run(main, feed={"x": np.zeros(2, np.float32)},
                              fetch_list=[y])
        assert s.find_var("x") is not None
        np.testing.assert_allclose(s.find_var("x").get_tensor(),
                                   np.zeros(2))
    places = static.cpu_places()
    assert len(places) == 1


def test_dynamic_mode_untouched_after_disable(static_mode):
    paddle.disable_static()
    t = paddle.to_tensor([1.0, 2.0])
    assert float((t * 2).sum().numpy()) == 6.0
    assert paddle.in_dynamic_mode()


def test_append_backward_fetch_grads(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = paddle.mean(lin(x) ** 2)
        pairs = static.append_backward(loss)
    assert pairs and all(gv.name.endswith("@GRAD") for _, gv in pairs)
    xv = np.random.default_rng(1).standard_normal((4, 3)).astype(np.float32)
    outs = static.Executor().run(main, feed={"x": xv},
                                 fetch_list=[loss] + [g for _, g in pairs])
    # numpy oracle: d(mean((xW+b)^2)) = 2/N * x^T (xW+b), sum for b
    w = pairs[0][0].numpy() if pairs[0][0].numpy().shape == (3, 1) \
        else pairs[1][0].numpy()
    b = [p for p, _ in pairs if p.numpy().shape != (3, 1)][0].numpy()
    y = xv @ w + b
    gw = (2 / y.size) * xv.T @ y
    gb = (2 / y.size) * y.sum(0)
    got = {tuple(p.numpy().shape): g for (p, _), g in zip(pairs, outs[1:])}
    np.testing.assert_allclose(got[(3, 1)], gw, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got[(1,)], gb, rtol=1e-4, atol=1e-5)
    # a second run returns the SAME grads (no cross-run accumulation)
    outs2 = static.Executor().run(main, feed={"x": xv},
                                  fetch_list=[g for _, g in pairs])
    got2 = {tuple(p.numpy().shape): g
            for (p, _), g in zip(pairs, outs2)}
    np.testing.assert_allclose(got2[(3, 1)], gw, rtol=1e-4, atol=1e-5)


def test_gradients_wrt_input(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = paddle.sum(x * x)
        (gx,) = static.gradients([y], [x])
    xv = np.array([[1., 2.], [3., 4.]], np.float32)
    (g,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)
    # fetch by name works too
    (g2,) = static.Executor().run(main, feed={"x": xv},
                                  fetch_list=["x@GRAD"])
    np.testing.assert_allclose(g2, 2 * xv, rtol=1e-6)


def test_py_func_and_print(static_mode, capsys):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        out_decl = static.data("out_decl", [2, 2], "float32")
        y = static.py_func(lambda t: paddle.to_tensor(t.numpy() * 3.0),
                           x, out_decl)
        z = static.Print(y, message="dbg")
    # out_decl was a shape declaration — py_func unregisters it as a feed
    assert "out_decl" not in main._feeds
    xv = np.ones((2, 2), np.float32)
    (out,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(out, 3 * xv)
    assert "dbg" in capsys.readouterr().out
    with static.name_scope("block"):
        pass
    with pytest.raises(NotImplementedError):
        static.py_func(lambda t: t, x, out_decl, backward_func=lambda g: g)


def test_compiled_program_and_build_strategy(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    bs = static.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    cp = static.CompiledProgram(main, build_strategy=bs)
    cp = cp.with_data_parallel(loss_name=None)
    xv = np.ones((3, 2), np.float32)
    (out,) = static.Executor().run(cp, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, 2 * xv)
    assert "fuse_elewise_add_act_ops" in repr(bs)


def test_exponential_moving_average():
    # dygraph-style params (the EMA utility is backend-agnostic here)
    p = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    p.stop_gradient = False
    ema = static.ExponentialMovingAverage(0.5, parameter_list=[p])
    ema.update()                      # shadow = p = [1, 2]
    p._inplace_update(p._data * 0 + np.array([3.0, 6.0], np.float32))
    ema.update()                      # shadow = .5*[1,2] + .5*[3,6] = [2,4]
    with ema.apply():
        np.testing.assert_allclose(p.numpy(), [2.0, 4.0])
    np.testing.assert_allclose(p.numpy(), [3.0, 6.0])  # restored
    with ema.apply(need_restore=False):
        pass
    np.testing.assert_allclose(p.numpy(), [2.0, 4.0])


def test_weight_norm_param_attr_and_ipu_stubs():
    attr = static.WeightNormParamAttr(dim=0, name="w")
    assert attr.dim == 0 and isinstance(attr, static.ParamAttr)
    s = static.IpuStrategy()
    s.set_graph_config(num_ipus=1)
    with pytest.raises(RuntimeError, match="IPU backend"):
        static.IpuCompiledProgram(None)
    with pytest.raises(RuntimeError, match="IPU backend"):
        static.ipu_shard_guard(0)


@pytest.mark.slow
def test_static_nn_builders(static_mode):
    """static.nn legacy layer builders (reference: static/nn/common.py)
    record into a Program and replay correctly."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 6], "float32")
        h = static.nn.fc(x, 8, activation="relu")
        out = static.nn.fc(h, 2)
    xv = np.random.default_rng(0).standard_normal((4, 6)).astype(np.float32)
    (res,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[out])
    assert res.shape == (4, 2) and np.isfinite(res).all()

    # dygraph behavior of the other builders, numpy oracles
    paddle.disable_static()
    img = paddle.to_tensor(
        np.random.default_rng(1).normal(size=(2, 4, 8, 8)).astype("float32"))
    assert list(static.nn.conv2d(img, 6, 3, padding=1).shape) == [2, 6, 8, 8]
    assert list(static.nn.conv2d_transpose(img, 6, filter_size=3,
                                           stride=2).shape) == [2, 6, 17, 17]
    assert list(static.nn.batch_norm(img).shape) == [2, 4, 8, 8]
    assert list(static.nn.layer_norm(img, begin_norm_axis=2).shape) == \
        [2, 4, 8, 8]
    assert list(static.nn.group_norm(img, 2).shape) == [2, 4, 8, 8]
    assert list(static.nn.instance_norm(img).shape) == [2, 4, 8, 8]
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
    assert list(static.nn.embedding(ids, (10, 5)).shape) == [2, 2, 5]
    a = paddle.to_tensor(
        np.random.default_rng(2).normal(size=(4, 6)).astype("float32"))
    b = paddle.to_tensor(
        np.random.default_rng(3).normal(size=(4, 5)).astype("float32"))
    assert list(static.nn.bilinear_tensor_product(a, b, 7).shape) == [4, 7]
    assert list(static.nn.prelu(img, "channel").shape) == [2, 4, 8, 8]

    # row_conv oracle: out[t] = sum_i in[t+i] * w[i]
    seq = paddle.to_tensor(
        np.random.default_rng(4).normal(size=(1, 5, 3)).astype("float32"))
    rc = static.nn.row_conv(seq, 1)
    assert list(rc.shape) == [1, 5, 3]

    # spectral_norm drives sigma toward 1
    w = paddle.to_tensor(
        np.random.default_rng(5).normal(size=(5, 8)).astype("float32"))
    sn = static.nn.spectral_norm(w, power_iters=10)
    assert abs(np.linalg.svd(sn.numpy(), compute_uv=False)[0] - 1) < 0.05

    # nce returns per-sample positive loss
    lbl = paddle.to_tensor(np.array([[1], [2], [0], [3]], np.int64))
    loss = static.nn.nce(a, lbl, 10, num_neg_samples=4)
    assert list(loss.shape) == [4, 1] and float(loss.numpy().min()) > 0

    # static_pylayer custom backward
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    t.stop_gradient = False
    o = static.nn.static_pylayer(lambda v: v * 2, [t],
                                 backward_fn=lambda g: g * 3)
    o.sum().backward()
    np.testing.assert_allclose(t.grad.numpy(), 3 * np.ones((2, 2)))

    # descoped tiers say why
    with pytest.raises(NotImplementedError, match="LoD"):
        static.nn.sequence_pool(a, "max")
    with pytest.raises(NotImplementedError, match="parameter-server"):
        static.nn.sparse_embedding(a)


def test_static_serialization_roundtrip(static_mode, tmp_path):
    """serialize_program -> StableHLO artifact -> deserialize + run
    (reference: static/io.py serialize/deserialize)."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        y = paddle.tanh(lin(x))
        dead = paddle.exp(x)      # not fetched: normalize_program prunes
    normalized = static.normalize_program(main, [x], [y])
    assert len(normalized._nodes) < len(main._nodes)
    blob = static.serialize_program([x], [y], program=main)
    pblob = static.serialize_persistables([x], [y], program=main)
    static.save_to_file(str(tmp_path / "prog.bin"), blob)
    paddle.disable_static()
    dp = static.deserialize_program(
        static.load_from_file(str(tmp_path / "prog.bin")))
    xv = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
    (out,) = dp.run({"x": xv})
    ref = paddle.tanh(lin(paddle.to_tensor(xv))).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    (out2,) = dp.run({"x": xv[:2]})      # symbolic batch dim
    assert out2.shape == (2, 3)
    with pytest.raises(ValueError, match="missing feeds"):
        dp.run({})

    # persistables roundtrip through set_program_state
    import pickle
    state = pickle.loads(pblob)["state"]
    static.set_program_state(main, {k: v * 0 for k, v in state.items()})
    assert all(np.all(np.asarray(p._data) == 0)
               for p in main.parameters())
    static.deserialize_persistables(main, pblob)
    got = {k: np.asarray(v._data) for k, v in main.state_dict().items()}
    for k in state:
        np.testing.assert_allclose(got[k], state[k])


def test_static_metrics_and_misc(static_mode):
    paddle.disable_static()
    pred = paddle.to_tensor(
        np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32))
    lbl = paddle.to_tensor(np.array([[1], [0], [0]], np.int64))
    acc = static.accuracy(pred, lbl)
    np.testing.assert_allclose(float(acc.numpy()), 2 / 3, rtol=1e-5)
    a, batch_a, stats = static.auc(pred, lbl)
    np.testing.assert_allclose(float(a.numpy()), 1.0, atol=1e-3)
    # perfect separation -> 1.0; flip labels -> 0.0
    a2, _, _ = static.auc(pred, paddle.to_tensor(
        np.array([[0], [1], [1]], np.int64)))
    np.testing.assert_allclose(float(a2.numpy()), 0.0, atol=1e-3)
    gv = static.create_global_var([2], 7.0, "float32", persistable=True)
    assert gv.persistable and float(gv.numpy()[0]) == 7.0
    assert len(static.cuda_places()) >= 1
    with static.device_guard("gpu:0"):
        pass
    with pytest.raises(NotImplementedError):
        static.ctr_metric_bundle(pred, lbl)


def test_data_norm_accumulates_stats():
    """Round-5 ADVICE fix: data_norm must update its
    batch_size/batch_sum/batch_square_sum accumulators each training
    call (reference static/nn/common.py:461), persisted by name."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import static

    rng = np.random.default_rng(0)
    x = rng.normal(loc=5.0, scale=2.0, size=(64, 4)).astype(np.float32)
    xt = paddle.to_tensor(x)
    name = "dn_acc_test"
    # first call normalizes with the init stats (mean 0, scale 1)
    out1 = static.nn.data_norm(xt, name=name, data_layout="NHWC")
    np.testing.assert_allclose(out1.numpy(), x, rtol=1e-5, atol=1e-5)
    # after many accumulating calls the stats approach the data's
    # mean/second-moment, so the output is no longer the identity
    for _ in range(50):
        static.nn.data_norm(xt, name=name, data_layout="NHWC")
    out2 = static.nn.data_norm(xt, name=name, data_layout="NHWC")
    assert not np.allclose(out2.numpy(), x, atol=1e-2)
    # and the normalized output's mean drifts toward 0
    assert abs(out2.numpy().mean()) < abs(x.mean())


def test_data_norm_static_build(static_mode):
    """data_norm must still build+run inside a static program (the
    accumulator update is eager-only; static replay uses frozen
    stats)."""
    import numpy as np
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 4], "float32")
        out = static.nn.data_norm(x, data_layout="NHWC")
    exe = static.Executor()
    xv = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    (res,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(res, xv, rtol=1e-5, atol=1e-5)
