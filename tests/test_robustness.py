"""Robustness satellites (ISSUE 11): mid-flight SLO abort, the in-graph
non-finite logits guard, structured ``InvariantViolation`` pool
failures, and the preempt/requeue storm soak — all asserted on the
virtual clock, chip-free."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
import jax.numpy as jnp
from paddle_tpu.loadgen import (Driver, TraceRequest, VirtualClock,
                                WorkloadSpec, build_report,
                                trace_fingerprint)
from paddle_tpu.models import (Generator, LlamaForCausalLM,
                               llama_tiny_config)
from paddle_tpu.serving import (InvariantViolation, LLMEngine,
                                PagedKVPool)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _engine(model, clock, **kw):
    kw.setdefault("max_len", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("seed", 0)
    return LLMEngine(model, now_fn=clock.now, **kw)


def _reference_tokens(model, prompt, n, max_len=64):
    gen = Generator(model, max_len=max_len)
    out = gen.generate(paddle.to_tensor(np.asarray(prompt)[None],
                                        dtype="int64"),
                       max_new_tokens=n, temperature=0.0).numpy()
    return out[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# satellite: mid-flight SLO abort
# ---------------------------------------------------------------------------

def test_running_request_aborts_at_e2e_deadline(tiny_model):
    """A RUNNING request whose absolute e2e deadline passes must
    finalize at a step boundary (reason deadline_exceeded) with pages
    freed — not decode its remaining tokens for nobody."""
    clock = VirtualClock()
    eng = _engine(tiny_model, clock)
    rid = eng.add_request([1, 2, 3], max_new_tokens=20,
                          abort_after_s=0.05)
    steps = 0
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
        steps += 1
        assert steps < 100
    out = eng.outputs()[rid]
    assert out.status == "shed"
    assert out.finish_reason == "deadline_exceeded"
    assert 0 < len(out.token_ids) < 20, \
        "the abort fired mid-flight, after some tokens streamed"
    assert eng.metrics_snapshot()["deadline_aborts"] == 1
    assert eng.pool.free_pages == eng.pool.capacity
    eng.pool.check_invariants()


def test_abort_leaves_cow_shared_pages_and_survivor_intact(tiny_model):
    """Aborting one fork of a shared prompt prefix must release only
    the aborted sequence's refcounts: the surviving sharer keeps its
    pages and still produces the reference greedy continuation."""
    clock = VirtualClock()
    eng = _engine(tiny_model, clock, max_len=64, num_pages=33)
    prompt = list(range(1, 13))                  # 12 tokens, 3 full pages
    doomed = eng.add_request(prompt, max_new_tokens=24,
                             abort_after_s=0.05)
    clock.advance(0.01)
    eng.step()                                   # donor prompt committed
    survivor = eng.add_request(prompt, max_new_tokens=8)
    steps = 0
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
        steps += 1
        assert steps < 200
    outs = eng.outputs()
    assert outs[doomed].status == "shed"
    assert outs[doomed].finish_reason == "deadline_exceeded"
    assert eng.metrics.prefix_cache_hits.value >= 1, \
        "the survivor must actually have forked the shared prefix"
    assert outs[survivor].status == "finished"
    assert outs[survivor].token_ids == \
        _reference_tokens(tiny_model, prompt, 8)
    eng.pool.check_invariants()
    assert eng.pool.free_pages == eng.pool.capacity


def test_abort_after_s_rides_the_loadgen_trace(tiny_model):
    """WorkloadSpec.abort_after_s lands on every TraceRequest, is part
    of the fingerprint, and produces deadline_exceeded sheds in a run
    whose outputs exceed the abort window."""
    spec = WorkloadSpec(num_requests=8, seed=3, arrival="deterministic",
                        arrival_rate=100.0, prompt_len=(4, 8),
                        output_len=(16, 20), abort_after_s=0.08,
                        vocab_size=128)
    trace = spec.compile()
    assert all(r.abort_after_s == 0.08 for r in trace)
    assert trace_fingerprint(trace) != trace_fingerprint(
        dataclasses.replace(spec, abort_after_s=None).compile())
    clock = VirtualClock()
    eng = _engine(tiny_model, clock, max_num_seqs=4)
    result = Driver(eng, clock, step_time_s=0.01).run(trace)
    report = build_report(result, spec=spec, trace=trace)
    assert report["requests"]["unresolved"] == 0
    aborted = [r for r in result.records
               if r.finish_reason == "deadline_exceeded"]
    assert aborted, "the tight abort SLO must have fired"
    assert result.metrics["deadline_aborts"] == len(aborted)
    assert eng.pool.free_pages == eng.pool.capacity
    with pytest.raises(ValueError, match="abort_after_s"):
        WorkloadSpec(abort_after_s=0.0)


# ---------------------------------------------------------------------------
# satellite: non-finite logits guard
# ---------------------------------------------------------------------------

def _poison(eng):
    """Plant one NaN in a projection weight: every row's logits go
    non-finite and the isfinite guard must catch them at commit."""
    lyr = eng.params["layers"][0]
    lyr["q"] = lyr["q"].at[0, 0].set(jnp.nan)


def test_nonfinite_logits_abort_structured_not_token_zero(tiny_model):
    clock = VirtualClock()
    eng = _engine(tiny_model, clock)
    _poison(eng)
    r1 = eng.add_request([1, 2, 3], max_new_tokens=4)
    r2 = eng.add_request([4, 5, 6, 7], max_new_tokens=4)
    steps = 0
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
        steps += 1
        assert steps < 50, "poisoned rows must abort, not loop"
    for rid in (r1, r2):
        out = eng.outputs()[rid]
        assert out.status == "aborted"
        assert out.finish_reason == "nonfinite_logits"
        assert out.token_ids == [], \
            "no garbage token 0 may be committed from NaN logits"
    assert eng.metrics_snapshot()["nonfinite_rows"] == 2
    assert eng.pool.free_pages == eng.pool.capacity
    eng.pool.check_invariants()


def test_nonfinite_guard_in_burst_mode(tiny_model):
    """The burst loop carries the per-row finite flag through its
    iterations: a poisoned burst commits NOTHING and aborts."""
    clock = VirtualClock()
    eng = _engine(tiny_model, clock, burst_tokens=4)
    _poison(eng)
    rid = eng.add_request([1, 2, 3], max_new_tokens=8)
    steps = 0
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
        steps += 1
        assert steps < 50
    out = eng.outputs()[rid]
    assert out.status == "aborted"
    assert out.finish_reason == "nonfinite_logits"
    assert out.token_ids == []
    assert eng.metrics_snapshot()["nonfinite_rows"] == 1
    assert eng.pool.free_pages == eng.pool.capacity
    eng.pool.check_invariants()


def test_healthy_engine_never_flags_nonfinite(tiny_model):
    clock = VirtualClock()
    eng = _engine(tiny_model, clock)
    eng.add_request([1, 2, 3], max_new_tokens=6)
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
    assert eng.metrics_snapshot()["nonfinite_rows"] == 0


# ---------------------------------------------------------------------------
# satellite: structured InvariantViolation
# ---------------------------------------------------------------------------

def test_invariant_violation_carries_pool_snapshot():
    p = PagedKVPool(1, 2, 8, num_pages=9, page_size=4)
    p.allocate("a", 8)
    p.fork("b", "a", 8)
    p.check_invariants()
    p._refcounts[p.block_table("a")[0]] += 1       # corrupt a refcount
    with pytest.raises(InvariantViolation, match="refcount") as ei:
        p.check_invariants()
    err = ei.value
    assert isinstance(err, AssertionError), \
        "InvariantViolation must remain AssertionError-compatible"
    snap = err.snapshot
    assert snap["offending_pages"] == [p.block_table("a")[0]]
    assert snap["capacity"] == 8
    assert snap["free_list_size"] == p.free_pages
    assert snap["used_pages"] == 2
    assert isinstance(snap["refcounts"], dict) and snap["refcounts"]
    assert "pinned" in snap and snap["pinned"] == []
    # the message alone is triageable (reason + key stats)
    assert "offending_pages" in str(err)


def test_invariant_violation_names_leaked_free_page():
    p = PagedKVPool(1, 2, 8, num_pages=9, page_size=4)
    p.allocate("a", 4)
    page = p.block_table("a")[0]
    p._free.append(page)                           # page mapped AND free
    with pytest.raises(InvariantViolation, match="mapped and free") as ei:
        p.check_invariants()
    assert page in ei.value.snapshot["offending_pages"]


# ---------------------------------------------------------------------------
# satellite: preempt/requeue storm soak
# ---------------------------------------------------------------------------

def test_soak_preempt_requeue_storm_no_leak_token_identity(tiny_model):
    """Hundreds of virtual-clock steps cycling admission -> preemption
    -> requeue on a low-watermark pool: the driver audits
    ``check_invariants`` EVERY step (a failure raises with the pool
    snapshot), no page leaks, and every eventually-finished request is
    greedy token-identical to the sequential Generator."""
    rng = np.random.default_rng(0)
    prompts = {}
    trace = []
    for w in range(10):                            # 10 waves x 6 requests
        for i in range(6):
            rid = f"storm-{w}-{i}"
            n = int(rng.integers(4, 11))
            prompts[rid] = [int(x) for x in rng.integers(0, 128, (n,))]
            trace.append(TraceRequest(
                rid, 0.04 * w + 0.005 * i, tuple(prompts[rid]),
                max_new_tokens=int(rng.integers(8, 13))))
    clock = VirtualClock()
    # 10 usable pages, 4 row slots, low watermarks: sustained admission
    # -> preemption -> requeue churn for the whole storm
    eng = _engine(tiny_model, clock, num_pages=11, max_num_seqs=4,
                  high_watermark=0.85, low_watermark=0.4)
    result = Driver(eng, clock, step_time_s=0.002, check_every=1,
                    max_steps=5000).run(trace)
    assert result.steps >= 200, \
        f"the storm must churn for hundreds of steps, got {result.steps}"
    assert result.invariant_checks == result.steps, \
        "the pool must have been audited on EVERY step"
    assert result.metrics["preemptions"] >= 5, \
        "the low-watermark pool must have preempted repeatedly"
    by_id = {r.request_id: r for r in result.records}
    finished = [rid for rid, r in by_id.items() if r.status == "finished"]
    assert len(finished) == len(trace), "the storm must drain completely"
    # zero page leak after the storm
    assert eng.pool.free_pages == eng.pool.capacity
    assert eng.pool.used_pages == 0
    eng.pool.check_invariants()
    # greedy token identity for every eventually-finished request
    outs = eng.outputs()
    for rid in finished:
        want = _reference_tokens(tiny_model, prompts[rid],
                                 by_id[rid].max_new_tokens)
        assert outs[rid].token_ids == want, \
            f"{rid} diverged after {by_id[rid].num_preemptions} preemptions"
