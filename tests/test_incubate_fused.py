"""Fused-op API surface tests (incubate.nn.functional parity)."""
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF


def test_fused_rms_norm_with_residual():
    x = paddle.to_tensor(np.random.randn(2, 8, 64).astype(np.float32),
                         stop_gradient=False)
    w = paddle.to_tensor(np.ones(64, np.float32), stop_gradient=False)
    r = paddle.to_tensor(np.random.randn(2, 8, 64).astype(np.float32))
    out, res = IF.fused_rms_norm(x, w, residual=r)
    pre = x.numpy() + r.numpy()
    np.testing.assert_allclose(res.numpy(), pre, rtol=1e-5)
    var = (pre ** 2).mean(-1, keepdims=True)
    np.testing.assert_allclose(out.numpy(), pre / np.sqrt(var + 1e-6),
                               rtol=1e-4, atol=1e-4)
    out.sum().backward()
    assert x.grad is not None and w.grad is not None


def test_fused_layer_norm():
    x = paddle.to_tensor(np.random.randn(4, 32).astype(np.float32))
    w = paddle.to_tensor(np.random.randn(32).astype(np.float32))
    b = paddle.to_tensor(np.random.randn(32).astype(np.float32))
    out = IF.fused_layer_norm(x, w, b)
    xn = x.numpy()
    mu, var = xn.mean(-1, keepdims=True), xn.var(-1, keepdims=True)
    ref = (xn - mu) / np.sqrt(var + 1e-5) * w.numpy() + b.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_fused_rotary_position_embedding():
    q = paddle.to_tensor(np.random.randn(2, 16, 4, 32).astype(np.float32))
    k = paddle.to_tensor(np.random.randn(2, 16, 4, 32).astype(np.float32))
    oq, ok, ov = IF.fused_rotary_position_embedding(q, k)
    assert ov is None
    assert oq.shape == q.shape and ok.shape == k.shape
    # norm-preserving per rotated pair
    np.testing.assert_allclose(
        np.linalg.norm(oq.numpy(), axis=-1),
        np.linalg.norm(q.numpy(), axis=-1), rtol=1e-4)


def test_fused_bias_act_swiglu_and_matmul_bias():
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    b = paddle.to_tensor(np.random.randn(16).astype(np.float32))
    out = IF.fused_bias_act(x, b, act_method="swiglu")
    a = x.numpy() + b.numpy()
    u, g = a[:, :8], a[:, 8:]
    ref = u / (1 + np.exp(-u)) * g
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    w = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    bias = paddle.to_tensor(np.random.randn(8).astype(np.float32))
    y = IF.fused_matmul_bias(x, w, bias)
    np.testing.assert_allclose(y.numpy(), x.numpy() @ w.numpy() + bias.numpy(),
                               rtol=1e-4, atol=1e-4)
