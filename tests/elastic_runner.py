"""Worker script: elastic restart + checkpoint-resume end to end.

Spawned by the launch CLI with --max_restart >= 1. Incarnation 1 of rank
1 CRASHES mid-training (after step 3); the controller restarts the pod;
incarnation 2 resumes from the per-step checkpoint and finishes. The
parent test asserts the full trajectory matches an uninterrupted run —
the reference's elastic manager contract (fleet/elastic/manager.py:125:
detect failure, restart workers, training resumes from state).
"""
import json
import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed.collective import ReduceOp  # noqa: E402

TOTAL_STEPS = 6
CRASH_AFTER = 3


def main():
    dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()
    workdir = os.environ["ELASTIC_DIR"]
    ckpt = os.path.join(workdir, f"ckpt_rank{rank}.npz")
    marker = os.path.join(workdir, f"crashed_rank{rank}")

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    w_true = np.arange(4, dtype=np.float32).reshape(4, 1)
    y = x @ w_true
    shard = 8 // world
    xs = paddle.to_tensor(x[rank * shard:(rank + 1) * shard])
    ys = paddle.to_tensor(y[rank * shard:(rank + 1) * shard])

    lin = paddle.nn.Linear(4, 1)
    lin.weight._data = jax.numpy.zeros((4, 1))
    lin.bias._data = jax.numpy.zeros((1,))
    opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                               learning_rate=0.1)
    start = 0
    if os.path.exists(ckpt):          # resume after the elastic restart
        data = np.load(ckpt)
        lin.weight._data = jax.numpy.asarray(data["w"])
        lin.bias._data = jax.numpy.asarray(data["b"])
        start = int(data["step"])

    losses = []
    for step in range(start, TOTAL_STEPS):
        loss = paddle.nn.functional.mse_loss(lin(xs), ys)
        loss.backward()
        for p in lin.parameters():
            if p.grad is not None:
                dist.all_reduce(p.grad, op=ReduceOp.AVG)
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
        np.savez(ckpt, w=np.asarray(lin.weight.numpy()),
                 b=np.asarray(lin.bias.numpy()), step=step + 1)
        if rank == 1 and step + 1 == CRASH_AFTER \
                and not os.path.exists(marker):
            open(marker, "w").write("1")
            os._exit(17)              # simulated hard failure

    if rank == 0:
        out = {
            "resumed_from": start,
            "final_w": np.asarray(lin.weight.numpy()).ravel().tolist(),
            "final_b": np.asarray(lin.bias.numpy()).ravel().tolist(),
            "losses": losses,
        }
        # both incarnations of rank 0 write; the LAST (resumed) one wins
        with open(os.path.join(workdir, "result.json"), "w") as f:
            json.dump(out, f)


if __name__ == "__main__":
    main()
