"""Distributed stack tests on the 8-device virtual CPU mesh
(SURVEY.md §4: multi-device single-host stands in for the fabric)."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.distributed import Replicate, Shard, ProcessMesh
from paddle_tpu.distributed import fleet


@pytest.fixture(autouse=True)
def _reset_fleet():
    yield
    fleet.set_hybrid_communicate_group(None)
    fleet._fleet_state.update(strategy=None, hcg=None, initialized=False)


def test_mesh_basics():
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    assert mesh.shape == [2, 4]
    assert mesh.dim_names == ["dp", "mp"]
    assert mesh.process_ids == list(range(8))
    assert mesh.get_dim_size("mp") == 4
    sub = mesh.get_mesh_with_dim("mp", 0)
    assert sub.shape == [2]


def test_shard_tensor_and_reshard():
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    xs = dist.shard_tensor(x, mesh, [Shard(0), Replicate()])
    # value preserved
    np.testing.assert_array_equal(xs.numpy(), x.numpy())
    # 2 dp shards of 4 rows each; each placed on 4 mp devices
    shard_shapes = {s.data.shape for s in xs._data.addressable_shards}
    assert shard_shapes == {(4, 4)}
    # reshard to fully sharded on dim1 over mp
    xr = dist.reshard(xs, mesh, [Shard(0), Shard(1)])
    assert {s.data.shape for s in xr._data.addressable_shards} == {(4, 1)}
    np.testing.assert_array_equal(xr.numpy(), x.numpy())


def test_sharded_matmul_correctness():
    # TP matmul: x replicated, w col-sharded → y col-sharded, same values
    mesh = dist.init_mesh({"mp": 8})
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    w = paddle.to_tensor(np.random.randn(16, 32).astype(np.float32))
    ws = dist.shard_tensor(w, mesh, [Shard(1)])
    y = paddle.matmul(x, ws)
    np.testing.assert_allclose(y.numpy(), x.numpy() @ w.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_dist_tensor_autograd():
    # grads flow through sharded params
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    w = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
    ws = dist.shard_tensor(w, mesh, [Replicate(), Shard(1)], stop_gradient=False)
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    loss = paddle.matmul(x, ws).sum()
    loss.backward()
    assert ws.grad is not None
    np.testing.assert_allclose(
        ws.grad.numpy(), np.ones((2, 8)).T @ np.ones((2, 8)) * 0
        + x.numpy().T @ np.ones((2, 8)), rtol=1e-5)


def test_fleet_topology():
    topo = fleet.CommunicateTopology(["pp", "dp", "sharding", "sep", "mp"],
                                     [2, 2, 1, 1, 2])
    assert topo.world_size() == 8
    assert topo.get_coord(0) == (0, 0, 0, 0, 0)
    c = topo.get_coord(5)
    assert topo.get_rank(pp=c.pp, dp=c.dp, sharding=0, sep=0, mp=c.mp) == 5
    comm = topo.get_comm_list("mp")
    assert [0, 1] in comm and len(comm) == 4


def test_fleet_init_and_hcg():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 1, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.mesh.shape == [2, 2, 1, 1, 2]


def test_column_row_parallel_linear_parity():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    col = fleet.ColumnParallelLinear(16, 32, has_bias=True, gather_output=False)
    row = fleet.RowParallelLinear(32, 16, has_bias=True, input_is_parallel=True)
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    y = row(col(x))
    # parity vs dense computation with the same weights
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() \
        + row.bias.numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-4)
    # TP backward
    y.sum().backward()
    assert col.weight.grad is not None and row.weight.grad is not None


def test_vocab_parallel_embedding():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    emb = fleet.VocabParallelEmbedding(64, 16)
    ids = paddle.to_tensor(np.array([[1, 5, 63]]), dtype="int64")
    out = emb(ids)
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1], rtol=1e-6)


def test_data_parallel_wrapper():
    mesh = dist.init_mesh({"dp": 8})
    lin = nn.Linear(8, 4)
    dp = paddle.DataParallel(lin, mesh=mesh)
    x = dp.scatter_batch(paddle.to_tensor(np.random.randn(16, 8).astype(np.float32)))
    assert {s.data.shape for s in x._data.addressable_shards} == {(2, 8)}
    y = dp(x)
    loss = y.sum()
    loss.backward()
    assert lin.weight.grad is not None


def test_group_sharded_parallel_stage3():
    mesh = dist.init_mesh({"sharding": 8})
    dist.set_mesh(mesh)
    m = nn.Linear(16, 16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    m2, opt2, _ = dist.group_sharded_parallel(m, opt, "p_g_os")
    # params sharded on dim0
    assert {s.data.shape for s in m.weight._data.addressable_shards} == {(2, 16)}
    # training still works
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    loss = F.mse_loss(m(x), paddle.to_tensor(np.zeros((4, 16), np.float32)))
    loss.backward()
    opt.step()
    opt.clear_grad()
    st = opt._param_state(m.weight)
    assert {s.data.shape for s in st["moment1"]._data.addressable_shards} == {(2, 16)} \
        if hasattr(st["moment1"], "_data") else True


@pytest.mark.slow
def test_parallelize_plan():
    from paddle_tpu.distributed.auto_parallel import ColWiseParallel, RowWiseParallel
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=1))
    plan = {
        "model.layers.*.self_attn.q_proj": ColWiseParallel(),
        "model.layers.*.self_attn.k_proj": ColWiseParallel(),
        "model.layers.*.self_attn.v_proj": ColWiseParallel(),
        "model.layers.*.self_attn.o_proj": RowWiseParallel(),
        "model.layers.*.mlp.gate_proj": ColWiseParallel(),
        "model.layers.*.mlp.up_proj": ColWiseParallel(),
        "model.layers.*.mlp.down_proj": RowWiseParallel(),
    }
    dist.parallelize(m, mesh, {"mp_config": {"parallelize_plan": plan}})
    qw = m.model.layers[0].self_attn.q_proj.weight
    assert {s.data.shape for s in qw._data.addressable_shards} == {(128, 32)}
    ids = paddle.to_tensor(np.random.randint(0, 512, (2, 16)), dtype="int64")
    logits, loss = m(ids, labels=ids)
    loss.backward()
    assert qw.grad is not None


def test_distributed_checkpoint_roundtrip(tmp_path):
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    m = nn.Linear(8, 8)
    dist.shard_parameter(m.weight, mesh, [Replicate(), Shard(1)])
    w0 = m.weight.numpy().copy()
    dist.save_state_dict(m.state_dict(), str(tmp_path / "ckpt"))
    # perturb then load back; resharded to current placement
    m.weight._data = m.weight._data * 0.0
    dist.load_state_dict(m.state_dict(), str(tmp_path / "ckpt"))
    np.testing.assert_allclose(m.weight.numpy(), w0, rtol=1e-6)
    assert {s.data.shape for s in m.weight._data.addressable_shards} == {(8, 2)}


def test_pipeline_layer_stages():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 1, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    layers = [nn.Linear(8, 8) for _ in range(4)]
    pp = fleet.PipelineLayer(layers=layers, num_stages=2)
    assert pp.get_stage_from_index(0) == 0 and pp.get_stage_from_index(3) == 1
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    y = pp(x)
    assert y.shape == [2, 8]
    # parity with a numpy sequential run of the same weights
    ref = x.numpy()
    for l in layers:
        ref = ref @ l.weight.numpy() + l.bias.numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-5)
    # backward crosses stage boundaries
    y.sum().backward()
    assert layers[0].weight.grad is not None


def test_eager_collective_api():
    dist.init_parallel_env()
    t = paddle.to_tensor(np.ones(4, np.float32))
    dist.all_reduce(t)
    np.testing.assert_array_equal(t.numpy(), np.ones(4))
    out = []
    dist.all_gather(out, t)
    assert len(out) >= 1


def test_cross_mesh_reshard():
    """reshard between DIFFERENT meshes (reference: same_status +
    global<->sub-mesh reshard functions, paddle/phi/core/distributed/
    auto_parallel/reshard/): a tensor sharded on mesh A lands on mesh B
    with values intact and metadata updated."""
    import jax
    from paddle_tpu.distributed.mesh import ProcessMesh
    from paddle_tpu.distributed import Replicate, Shard

    devs = [d.id for d in jax.devices()]
    mesh_a = ProcessMesh(np.asarray(devs).reshape(2, 4), ["dp", "mp"])
    mesh_b = ProcessMesh(np.asarray(devs[:4]), ["mp"])      # sub-mesh
    mesh_c = ProcessMesh(np.asarray(devs[::-1]).reshape(4, 2),
                         ["mp", "dp"])                      # permuted order

    val = np.arange(32, dtype=np.float32).reshape(8, 4)
    t = dist.shard_tensor(paddle.to_tensor(val), mesh_a,
                          [Shard(0), Shard(1)])
    # global -> sub-mesh
    sub = dist.reshard(t, mesh_b, [Shard(0)])
    np.testing.assert_array_equal(np.asarray(sub.numpy()), val)
    assert sub._dist_attr[0] == mesh_b
    # sub-mesh -> global (different shape AND device order: same_status)
    back = dist.reshard(sub, mesh_c, [Shard(1), Replicate()])
    np.testing.assert_array_equal(np.asarray(back.numpy()), val)
    assert back._dist_attr[0] == mesh_c
    # gradients still flow through the cross-mesh hop
    t2 = dist.shard_tensor(paddle.to_tensor(val), mesh_a,
                           [Shard(0), Replicate()])
    t2.stop_gradient = False
    y = dist.reshard(t2, mesh_b, [Replicate()])
    (y * y).sum().backward()
    np.testing.assert_allclose(np.asarray(t2.grad.numpy()), 2 * val)
