"""Custom-kernel override surface (round-2 verdict 'weak #2': the registry
was vestigial — only 14 primitive ops were reachable by override_kernel).

Reference property being recovered: every kernel is replaceable
(paddle/phi/core/kernel_registry.h:196 PD_REGISTER_KERNEL overriding a
backend). Ops routed through ``op_call`` resolve their body from ``OPS``
at call time, so a swap is visible eagerly, under jit tracing, and through
autograd."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.dispatch import OPS, override_kernel


@pytest.fixture
def restore_ops():
    saved = dict(OPS)
    yield
    OPS.clear()
    OPS.update(saved)


def test_registry_covers_op_families(restore_ops):
    """The op families converted to registry routing are present."""
    import paddle_tpu.tensor.math  # noqa: F401 — populates at import
    for name in ("add", "multiply", "exp", "log", "sum", "mean", "matmul",
                 "relu", "sigmoid", "softmax", "gelu", "linear", "conv2d",
                 "layer_norm", "rms_norm",
                 "scaled_dot_product_attention"):
        assert name in OPS, name
    assert len(OPS) > 100, len(OPS)


def test_softmax_override_eager_jit_grad(restore_ops):
    """Swap softmax for a marker body: eager, compiled (to_static), and
    gradient paths all pick the replacement up."""
    calls = {"n": 0}

    def my_softmax(a, axis=-1):
        calls["n"] += 1
        e = jnp.exp(a - a.max(axis=axis, keepdims=True))
        return 2.0 * e / e.sum(axis=axis, keepdims=True)   # marker: 2x

    old = override_kernel("softmax", my_softmax)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
        (4, 5)).astype(np.float32))

    # eager
    out = F.softmax(x, axis=1)
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2 * 4,
                               rtol=1e-5)
    assert calls["n"] == 1

    # grad flows through the override
    x.stop_gradient = False
    (F.softmax(x, axis=1) * paddle.to_tensor(
        np.ones((4, 5), np.float32))).sum().backward()
    assert x.grad is not None

    # compiled: to_static traces the override
    @paddle.jit.to_static
    def f(t):
        return F.softmax(t, axis=-1)

    out = f(paddle.to_tensor(np.zeros((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2 * 2,
                               rtol=1e-5)

    # restore and verify the default is back
    override_kernel("softmax", old)
    out = F.softmax(paddle.to_tensor(np.zeros((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2, rtol=1e-5)


def test_binop_and_matmul_override(restore_ops):
    override_kernel("multiply", lambda a, b: a * b + 100.0)
    out = paddle.multiply(paddle.to_tensor(np.asarray([2.0], np.float32)),
                          paddle.to_tensor(np.asarray([3.0], np.float32)))
    assert float(out.numpy()[0]) == pytest.approx(106.0)

    seen = {}

    def my_matmul(a, b, transpose_x=False, transpose_y=False):
        seen["kwargs"] = (transpose_x, transpose_y)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    override_kernel("matmul", my_matmul)
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    b = paddle.to_tensor(np.ones((2, 3), np.float32))
    out = paddle.matmul(a, b, transpose_y=True)
    assert tuple(out.shape) == (2, 2)
    # the override received the full call signature, not just arrays
    assert seen["kwargs"] == (False, True)


def test_train_step_compiles_override(restore_ops):
    """The fused TrainStep (jit) executes the swapped body too."""
    override_kernel("relu", lambda a: jnp.maximum(a, 0) + 1.0)
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.ReLU())
    opt = paddle.optimizer.SGD(parameters=model.parameters(),
                               learning_rate=0.0)
    step = paddle.jit.TrainStep(
        model, lambda xb: model(xb).sum(), opt)
    out = step(paddle.to_tensor(np.zeros((2, 4), np.float32)))
    # relu(z)+1 summed over 2x4 with zero weights -> bias-only forward;
    # the +1 marker contributes exactly 8
    assert float(out.numpy()) >= 8.0 - 1e-5


def test_registry_reaches_public_op_count(restore_ops):
    """Round-3 verdict item 3: ~260 formerly closure-bound ops are now
    registry-routed; len(OPS) approximates the public op count."""
    import paddle_tpu.signal  # noqa: F401
    import paddle_tpu.tensor.einsum  # noqa: F401
    import paddle_tpu.geometric  # noqa: F401
    import paddle_tpu.incubate.nn.functional  # noqa: F401
    assert len(OPS) >= 350, len(OPS)
    for name in ("embedding", "dropout", "reshape", "concat",
                 "max_pool2d", "avg_pool2d", "group_norm", "batch_norm",
                 "conv2d_transpose", "cross_entropy", "argmax", "topk",
                 "svd", "solve", "stft", "einsum", "send_u_recv",
                 "fused_rms_norm", "segment_sum", "gather", "scatter",
                 "where", "interpolate", "grid_sample", "one_hot",
                 "index_select", "cumsum", "pad", "split", "stack"):
        assert name in OPS, name


def _check_override(op_name, call, expect_marker, grad_input=None):
    """Swap ``op_name`` for a body adding a +1000 marker; assert the
    public API call sees it eagerly, that grads still flow, and restore."""
    default = OPS[op_name]

    def marked(*args, **kwargs):
        return default(*args, **kwargs) + 1000.0

    old = override_kernel(op_name, marked)
    try:
        out = call()
        assert expect_marker(out), f"{op_name}: override not reached"
        if grad_input is not None:
            grad_input.stop_gradient = False
            out2 = call()
            out2.sum().backward()
            assert grad_input.grad is not None, f"{op_name}: no grad"
    finally:
        override_kernel(op_name, old)


@pytest.mark.slow
def test_override_one_op_per_family(restore_ops):
    """Round-3 verdict item 3's 'done' bar: override one op per family
    (manipulation, embedding, dropout-family, pooling, norm, conv, loss,
    search, linalg, reduction) and observe the swap from the public API."""
    rng = np.random.default_rng(0)

    # manipulation: reshape
    x = paddle.to_tensor(np.zeros((2, 3), np.float32))
    _check_override(
        "reshape", lambda: paddle.reshape(x, [3, 2]),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0),
        grad_input=x)

    # embedding
    ids = paddle.to_tensor(np.asarray([[0, 1]], np.int64))
    table = paddle.to_tensor(np.zeros((4, 8), np.float32))
    _check_override(
        "embedding", lambda: F.embedding(ids, table),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0),
        grad_input=table)

    # concat
    a = paddle.to_tensor(np.zeros((2, 2), np.float32))
    _check_override(
        "concat", lambda: paddle.concat([a, a], axis=0),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0),
        grad_input=a)

    # pooling
    img = paddle.to_tensor(np.zeros((1, 1, 4, 4), np.float32))
    _check_override(
        "max_pool2d", lambda: F.max_pool2d(img, 2),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0),
        grad_input=img)

    # norm family
    h = paddle.to_tensor(np.ones((2, 4), np.float32))
    w = paddle.to_tensor(np.ones((4,), np.float32))
    _check_override(
        "layer_norm", lambda: F.layer_norm(h, 4, w),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0, abs=1.0),
        grad_input=h)

    # conv family (transpose)
    ct_x = paddle.to_tensor(np.zeros((1, 2, 4, 4), np.float32))
    ct_w = paddle.to_tensor(np.zeros((2, 3, 3, 3), np.float32))
    _check_override(
        "conv2d_transpose",
        lambda: F.conv2d_transpose(ct_x, ct_w),
        lambda o: float(o.numpy().mean()) == pytest.approx(1000.0),
        grad_input=ct_w)

    # loss family
    logits = paddle.to_tensor(np.zeros((4, 5), np.float32))
    lbl = paddle.to_tensor(np.asarray([0, 1, 2, 3], np.int64))
    _check_override(
        "cross_entropy", lambda: F.cross_entropy(logits, lbl),
        lambda o: float(o.numpy()) > 900.0,
        grad_input=logits)

    # search family (argmax has no grad; marker only)
    s = paddle.to_tensor(np.asarray([[1.0, 2.0]], np.float32))
    _check_override(
        "argmax", lambda: paddle.argmax(s, axis=1),
        lambda o: int(o.numpy()[0]) == 1001)

    # linalg family
    m = paddle.to_tensor(np.eye(3, dtype=np.float32))
    _check_override(
        "inverse", lambda: paddle.inverse(m),
        lambda o: float(o.numpy().mean()) > 900.0,
        grad_input=m)

    # reduction with settings
    r = paddle.to_tensor(np.ones((2, 3), np.float32))
    _check_override(
        "sum", lambda: paddle.sum(r, axis=1),
        lambda o: float(o.numpy()[0]) == pytest.approx(1003.0),
        grad_input=r)


def test_override_dropout_under_jit(restore_ops):
    """Dropout routes through the registry including its PRNG key; a swap
    is visible both eagerly and under to_static."""
    def no_drop(a, key, *, p, axis, mode):
        return a * 0.0 + 7.0

    old = override_kernel("dropout", no_drop)
    try:
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        out = F.dropout(x, p=0.5, training=True)
        np.testing.assert_allclose(out.numpy(), 7.0)

        @paddle.jit.to_static
        def f(t):
            return F.dropout(t, p=0.5, training=True)

        np.testing.assert_allclose(f(x).numpy(), 7.0)
    finally:
        override_kernel("dropout", old)
