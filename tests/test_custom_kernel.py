"""Custom-kernel override surface (round-2 verdict 'weak #2': the registry
was vestigial — only 14 primitive ops were reachable by override_kernel).

Reference property being recovered: every kernel is replaceable
(paddle/phi/core/kernel_registry.h:196 PD_REGISTER_KERNEL overriding a
backend). Ops routed through ``op_call`` resolve their body from ``OPS``
at call time, so a swap is visible eagerly, under jit tracing, and through
autograd."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.dispatch import OPS, override_kernel


@pytest.fixture
def restore_ops():
    saved = dict(OPS)
    yield
    OPS.clear()
    OPS.update(saved)


def test_registry_covers_op_families(restore_ops):
    """The op families converted to registry routing are present."""
    import paddle_tpu.tensor.math  # noqa: F401 — populates at import
    for name in ("add", "multiply", "exp", "log", "sum", "mean", "matmul",
                 "relu", "sigmoid", "softmax", "gelu", "linear", "conv2d",
                 "layer_norm", "rms_norm",
                 "scaled_dot_product_attention"):
        assert name in OPS, name
    assert len(OPS) > 100, len(OPS)


def test_softmax_override_eager_jit_grad(restore_ops):
    """Swap softmax for a marker body: eager, compiled (to_static), and
    gradient paths all pick the replacement up."""
    calls = {"n": 0}

    def my_softmax(a, axis=-1):
        calls["n"] += 1
        e = jnp.exp(a - a.max(axis=axis, keepdims=True))
        return 2.0 * e / e.sum(axis=axis, keepdims=True)   # marker: 2x

    old = override_kernel("softmax", my_softmax)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
        (4, 5)).astype(np.float32))

    # eager
    out = F.softmax(x, axis=1)
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2 * 4,
                               rtol=1e-5)
    assert calls["n"] == 1

    # grad flows through the override
    x.stop_gradient = False
    (F.softmax(x, axis=1) * paddle.to_tensor(
        np.ones((4, 5), np.float32))).sum().backward()
    assert x.grad is not None

    # compiled: to_static traces the override
    @paddle.jit.to_static
    def f(t):
        return F.softmax(t, axis=-1)

    out = f(paddle.to_tensor(np.zeros((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2 * 2,
                               rtol=1e-5)

    # restore and verify the default is back
    override_kernel("softmax", old)
    out = F.softmax(paddle.to_tensor(np.zeros((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(), 2, rtol=1e-5)


def test_binop_and_matmul_override(restore_ops):
    override_kernel("multiply", lambda a, b: a * b + 100.0)
    out = paddle.multiply(paddle.to_tensor(np.asarray([2.0], np.float32)),
                          paddle.to_tensor(np.asarray([3.0], np.float32)))
    assert float(out.numpy()[0]) == pytest.approx(106.0)

    seen = {}

    def my_matmul(a, b, transpose_x=False, transpose_y=False):
        seen["kwargs"] = (transpose_x, transpose_y)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    override_kernel("matmul", my_matmul)
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    b = paddle.to_tensor(np.ones((2, 3), np.float32))
    out = paddle.matmul(a, b, transpose_y=True)
    assert tuple(out.shape) == (2, 2)
    # the override received the full call signature, not just arrays
    assert seen["kwargs"] == (False, True)


def test_train_step_compiles_override(restore_ops):
    """The fused TrainStep (jit) executes the swapped body too."""
    override_kernel("relu", lambda a: jnp.maximum(a, 0) + 1.0)
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.ReLU())
    opt = paddle.optimizer.SGD(parameters=model.parameters(),
                               learning_rate=0.0)
    step = paddle.jit.TrainStep(
        model, lambda xb: model(xb).sum(), opt)
    out = step(paddle.to_tensor(np.zeros((2, 4), np.float32)))
    # relu(z)+1 summed over 2x4 with zero weights -> bias-only forward;
    # the +1 marker contributes exactly 8
    assert float(out.numpy()) >= 8.0 - 1e-5
