"""Scan-over-layers training path (nn/scan_stack.py) + satellites.

Gates, mirroring the optimizer dispatch-gate style:
- parity: scanned vs unrolled llama-tiny logits are BITWISE equal under
  jit (the TrainStep regime — both paths compile to the same per-layer
  kernels); gradients match to float-reassociation tolerance (XLA fuses
  the scan backward's reductions differently than straight-line code);
- trace-size gate: the scanned forward's jaxpr equation count is
  INDEPENDENT of num_hidden_layers while the unrolled path grows
  linearly — the O(1)-in-depth claim, hard-checked;
- grad accumulation: TrainStep(accumulate_steps=K) equals one K×-batch
  step (≤1e-6 f32 on a linear-update optimizer) at ONE host dispatch
  per optimizer step;
- state_dict: per-layer names round-trip through the stacked storage in
  both directions;
- flag-off parity: FLAGS_scan_layers=False + FLAGS_remat_policy=none is
  the pre-scan model, bit for bit.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.flags import GLOBAL_FLAGS
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.nn.scan_stack import LayerStack, effective_remat_policy


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    GLOBAL_FLAGS.set("scan_layers", False)
    GLOBAL_FLAGS.set("remat_policy", "none")


def _build(scan, **cfg_kw):
    GLOBAL_FLAGS.set("scan_layers", scan)
    try:
        return LlamaForCausalLM(llama_tiny_config(**cfg_kw))
    finally:
        GLOBAL_FLAGS.set("scan_layers", False)


def _ids(batch=2, seq=16, vocab=512, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (batch, seq))


def _functional_logits(model):
    """Functionalize the model forward for jit/make_jaxpr."""
    params = dict(model.named_parameters())

    def f(arrs, ids_arr):
        saved = {k: p._data for k, p in params.items()}
        try:
            for k, p in params.items():
                p._data = arrs[k]
            with _ag.no_grad():
                return model(Tensor(ids_arr))._data
        finally:
            for k, p in params.items():
                p._data = saved[k]

    return f, {k: p._data for k, p in params.items()}


def _functional_loss(model):
    params = dict(model.named_parameters())

    def f(arrs, ids_arr):
        saved = {k: p._data for k, p in params.items()}
        try:
            for k, p in params.items():
                p._data = arrs[k]
            with _ag.no_grad():
                return model(Tensor(ids_arr), labels=Tensor(ids_arr))[1]._data
        finally:
            for k, p in params.items():
                p._data = saved[k]

    return f, {k: p._data for k, p in params.items()}


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_scan_logits_bitwise_under_jit():
    m1 = _build(False)
    m2 = _build(True)
    assert isinstance(m2.model.layers, LayerStack)
    missing, unexpected = m2.set_state_dict(m1.state_dict())
    assert not missing and not unexpected
    ids = jnp.asarray(_ids())
    f1, a1 = _functional_logits(m1)
    f2, a2 = _functional_logits(m2)
    o1 = jax.jit(f1)(a1, ids)
    o2 = jax.jit(f2)(a2, ids)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))


def test_scan_grads_match_unrolled_under_jit():
    m1 = _build(False)
    m2 = _build(True)
    m2.set_state_dict(m1.state_dict())
    ids = jnp.asarray(_ids())
    f1, a1 = _functional_loss(m1)
    f2, a2 = _functional_loss(m2)
    g1 = jax.jit(jax.grad(f1))(a1, ids)
    g2 = jax.jit(jax.grad(f2))(a2, ids)
    # per-layer grads: slice the stacked cotangent
    for i in (0, 1):
        q1 = np.asarray(g1[f"model.layers.{i}.self_attn.q_proj.weight"])
        q2 = np.asarray(
            g2["model.layers.self_attn.q_proj.weight"])[i]
        # XLA reassociates the scan backward's fused reductions — not
        # bitwise, but far inside any training-relevant tolerance
        np.testing.assert_allclose(q1, q2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(g1["model.embed_tokens.weight"]),
        np.asarray(g2["model.embed_tokens.weight"]), rtol=1e-5, atol=1e-6)


def test_scan_eager_tape_grads_land_on_stacked_params():
    """The eager path (no jit): one tape node for the whole scan, grads
    arrive leading-axis-stacked on the stacked Parameters."""
    m1 = _build(False)
    m2 = _build(True)
    m2.set_state_dict(m1.state_dict())
    ids = paddle.to_tensor(_ids(), dtype="int64")
    _, l1 = m1(ids, labels=ids)
    _, l2 = m2(ids, labels=ids)
    np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                               rtol=1e-6)
    l1.backward()
    l2.backward()
    for name in ("self_attn.q_proj.weight", "mlp.down_proj.weight",
                 "input_layernorm.weight"):
        stacked = m2.model.layers.stacked_parameter(name).grad
        assert stacked is not None
        for i in (0, 1):
            ref = dict(m1.named_parameters())[
                f"model.layers.{i}.{name}"].grad
            np.testing.assert_allclose(
                np.asarray(stacked._data[i]), np.asarray(ref._data),
                rtol=1e-5, atol=1e-6)


def test_flag_off_is_pre_scan_model():
    GLOBAL_FLAGS.set("scan_layers", False)
    GLOBAL_FLAGS.set("remat_policy", "none")
    m = LlamaForCausalLM(llama_tiny_config())
    from paddle_tpu import nn
    assert isinstance(m.model.layers, nn.LayerList)
    assert effective_remat_policy(False) == "none"
    names = set(dict(m.named_parameters()))
    assert "model.layers.0.self_attn.q_proj.weight" in names


# ---------------------------------------------------------------------------
# trace-size gate: O(1) in depth
# ---------------------------------------------------------------------------

def _eqn_count(model):
    f, arrs = _functional_logits(model)
    jaxpr = jax.make_jaxpr(f)(arrs, jnp.zeros((1, 8), jnp.int32))
    return len(jaxpr.eqns)


def test_scanned_jaxpr_size_independent_of_depth():
    shallow = _eqn_count(_build(True, num_hidden_layers=2))
    deep = _eqn_count(_build(True, num_hidden_layers=8))
    assert shallow == deep, (
        f"scanned forward must trace O(1) equations in depth "
        f"(2 layers: {shallow} vs 8 layers: {deep})")
    un_shallow = _eqn_count(_build(False, num_hidden_layers=2))
    un_deep = _eqn_count(_build(False, num_hidden_layers=8))
    per_layer = (un_deep - un_shallow) / 6
    assert per_layer >= 10, (
        "unrolled path stopped growing with depth — the gate's "
        "denominator vanished")
    # and the deep scanned program is smaller than even the shallow unroll
    assert deep < un_shallow


# ---------------------------------------------------------------------------
# state_dict round-trip
# ---------------------------------------------------------------------------

def test_state_dict_roundtrip_per_layer_names():
    m_un = _build(False)
    m_sc = _build(True)
    sd_un = m_un.state_dict()
    sd_sc = m_sc.state_dict()
    assert set(sd_un) == set(sd_sc)
    # unrolled -> scanned -> unrolled survives bitwise
    m_sc.set_state_dict(sd_un)
    m_un2 = _build(False)
    missing, unexpected = m_un2.set_state_dict(m_sc.state_dict())
    assert not missing and not unexpected
    for k, v in m_un.state_dict().items():
        np.testing.assert_array_equal(
            np.asarray(v._data), np.asarray(m_un2.state_dict()[k]._data),
            err_msg=k)


def test_layerstack_rejects_buffers_and_heterogeneity():
    from paddle_tpu import nn

    class WithBuffer(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.register_buffer("b", paddle.to_tensor(np.zeros(4, np.float32)))

    with pytest.raises(ValueError, match="buffers"):
        LayerStack([WithBuffer(), WithBuffer()])
    with pytest.raises(ValueError, match="identical"):
        LayerStack([nn.Linear(4, 4), nn.Linear(4, 8)])


# ---------------------------------------------------------------------------
# gradient accumulation
# ---------------------------------------------------------------------------

def _train_pair(opt_cls, K, **opt_kw):
    m1 = _build(False)
    m2 = _build(False)
    m2.set_state_dict(m1.state_dict())
    o1 = opt_cls(parameters=m1.parameters(), **opt_kw)
    o2 = opt_cls(parameters=m2.parameters(), **opt_kw)
    s1 = paddle.jit.TrainStep(m1, lambda x: m1(x, labels=x)[1], o1)
    s2 = paddle.jit.TrainStep(m2, lambda x: m2(x, labels=x)[1], o2,
                              accumulate_steps=K)
    return m1, m2, s1, s2


def test_grad_accumulation_matches_full_batch_sgd():
    m1, m2, s1, s2 = _train_pair(paddle.optimizer.SGD, K=4,
                                 learning_rate=0.1)
    ids = paddle.to_tensor(_ids(batch=8), dtype="int64")
    l1 = float(s1(ids).numpy())
    l2 = float(s2(ids).numpy())
    assert abs(l1 - l2) <= 1e-6
    sd1, sd2 = m1.state_dict(), m2.state_dict()
    for k in sd1:
        np.testing.assert_allclose(np.asarray(sd1[k]._data),
                                   np.asarray(sd2[k]._data),
                                   rtol=0, atol=1e-6, err_msg=k)


def test_grad_accumulation_adamw_tracks_full_batch():
    # Adam's g/sqrt(v) update amplifies float-level grad differences near
    # step 1 (m/sqrt(v) ~ sign(g)); the linear-optimizer test above is
    # the ≤1e-6 gate, this one pins the adaptive path to a sane band.
    m1, m2, s1, s2 = _train_pair(paddle.optimizer.AdamW, K=2,
                                 learning_rate=1e-3)
    ids = paddle.to_tensor(_ids(batch=8), dtype="int64")
    l1 = float(s1(ids).numpy())
    l2 = float(s2(ids).numpy())
    assert abs(l1 - l2) <= 1e-6
    sd1, sd2 = m1.state_dict(), m2.state_dict()
    for k in sd1:
        np.testing.assert_allclose(np.asarray(sd1[k]._data),
                                   np.asarray(sd2[k]._data),
                                   rtol=0, atol=1e-3, err_msg=k)


def test_grad_accumulation_one_dispatch_per_step():
    """PR-1 gate invariant: dispatches per optimizer step do not grow
    with K — the whole K-scan + update is ONE compiled call."""
    from paddle_tpu.io.prefetch import PIPELINE_METRICS
    from paddle_tpu.optimizer import fused
    m = _build(False)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    step = paddle.jit.TrainStep(m, lambda x: m(x, labels=x)[1], opt,
                                accumulate_steps=4)
    ids = paddle.to_tensor(_ids(batch=8), dtype="int64")
    step(ids)  # compile
    PIPELINE_METRICS.reset()
    before = fused.dispatch_count()
    step(ids)
    assert PIPELINE_METRICS.snapshot()["step_dispatches"] == 1
    # steady state launches no extra eager optimizer dispatches either
    assert fused.dispatch_count() == before


def test_grad_accumulation_ragged_tail_falls_back():
    """A drop_last=False tail batch that does not divide by K runs as
    one micro-batch (same mean-grad update) with a warning instead of
    crashing an epoch of training at its last step."""
    m1, m2, s1, s2 = _train_pair(paddle.optimizer.SGD, K=3,
                                 learning_rate=0.1)
    ids = paddle.to_tensor(_ids(batch=8), dtype="int64")  # 8 % 3 != 0
    with pytest.warns(UserWarning, match="without accumulation"):
        l2 = float(s2(ids).numpy())
    l1 = float(s1(ids).numpy())
    assert abs(l1 - l2) <= 1e-6
    sd1, sd2 = m1.state_dict(), m2.state_dict()
    for k in sd1:
        np.testing.assert_allclose(np.asarray(sd1[k]._data),
                                   np.asarray(sd2[k]._data),
                                   rtol=0, atol=1e-6, err_msg=k)


def test_scaler_explicit_unscale_not_applied_twice():
    """unscale_() followed by step() must unscale exactly once (the
    double-division bug would silently shrink every grad by 1/scale²)."""
    params = _scaler_params(8)
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=params)
    sc = paddle.amp.GradScaler(init_loss_scaling=4.0)
    sc.unscale_(opt)
    sc.step(opt)            # must NOT re-unscale
    sc.update()
    np.testing.assert_allclose(np.asarray(params[1].grad._data),
                               np.full((4, 4), 0.5, np.float32))
    sc.unscale_(opt)        # fresh step: allowed again after update()
    with pytest.raises(RuntimeError, match="already"):
        sc.unscale_(opt)    # double unscale before update() raises


# ---------------------------------------------------------------------------
# remat policies
# ---------------------------------------------------------------------------

def test_remat_policies_preserve_values():
    """Remat changes WHEN activations are (re)computed, never what they
    are: loss and grads agree across all three policies."""
    m = _build(True)
    ids = paddle.to_tensor(_ids(), dtype="int64")
    results = {}
    for pol in ("none", "dots_saveable", "full"):
        GLOBAL_FLAGS.set("remat_policy", pol)
        for p in m.parameters():
            p.clear_grad()
        _, loss = m(ids, labels=ids)
        loss.backward()
        g = m.model.layers.stacked_parameter(
            "self_attn.q_proj.weight").grad._data
        results[pol] = (float(loss.numpy()), np.asarray(g))
    base_l, base_g = results["none"]
    for pol in ("dots_saveable", "full"):
        l, g = results[pol]
        assert abs(l - base_l) <= 1e-6, pol
        np.testing.assert_allclose(g, base_g, rtol=1e-5, atol=1e-7,
                                   err_msg=pol)


def test_remat_policy_flag_validates():
    with pytest.raises(ValueError, match="remat_policy"):
        GLOBAL_FLAGS.set("remat_policy", "everything")
    assert GLOBAL_FLAGS.get("remat_policy") in (
        "none", "dots_saveable", "full")


def test_config_remat_maps_to_full():
    assert effective_remat_policy(True) == "full"
    GLOBAL_FLAGS.set("remat_policy", "dots_saveable")
    # an explicit flag wins over the legacy spelling
    assert effective_remat_policy(True) == "dots_saveable"


def test_flops_per_token_accounts_remat_recompute():
    m = _build(False)
    base = m.flops_per_token(128, remat_policy="none")
    full = m.flops_per_token(128, remat_policy="full")
    n = sum(p.size for p in m.parameters())
    attn = 12 * m.config.num_hidden_layers * m.config.hidden_size * 128
    assert full - base == 2 * n + attn // 3
    assert m.flops_per_token(128, remat_policy="dots_saveable") == base


def test_config_validates_head_divisibility():
    from paddle_tpu.models import LlamaConfig
    with pytest.raises(ValueError, match="num_attention_heads"):
        LlamaConfig(hidden_size=100, num_attention_heads=3)
    with pytest.raises(ValueError, match="num_key_value_heads"):
        llama_tiny_config(num_attention_heads=4, num_key_value_heads=3)


# ---------------------------------------------------------------------------
# TrainStep compile forensics (profiler satellite)
# ---------------------------------------------------------------------------

def test_trainstep_records_compile_event():
    from paddle_tpu.core import native as nv
    nv.ensure_loaded()
    if not nv.AVAILABLE:
        pytest.skip("native runtime not built")
    from paddle_tpu import profiler
    m = _build(False, num_hidden_layers=1)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, lambda x: m(x, labels=x)[1], opt)
    ids = paddle.to_tensor(_ids(), dtype="int64")
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    prof.start()
    step(ids)          # first call: trace + compile -> `compile:` span
    step(ids)          # steady state: no new span
    prof.stop()
    names = [e[0] for e in prof.events()]
    compiles = [n for n in names if n.startswith("compile:TrainStep")]
    assert len(compiles) == 1, compiles
    assert step.last_compile_ms is not None and step.last_compile_ms > 0
    assert step.compile_ms_total >= step.last_compile_ms
    # a remat flag flip re-specializes — visible as another compile span
    GLOBAL_FLAGS.set("remat_policy", "full")
    prof2 = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    prof2.start()
    step(ids)
    prof2.stop()
    names2 = [e[0] for e in prof2.events()]
    assert any(n.startswith("compile:TrainStep") for n in names2)


# ---------------------------------------------------------------------------
# AmpScaler fused finiteness (amp satellite)
# ---------------------------------------------------------------------------

def _scaler_params(n=40):
    params = []
    for i in range(n):
        dt = "bfloat16" if i % 4 == 0 else "float32"
        t = paddle.to_tensor(np.zeros((4, 4), np.float32), dtype=dt)
        t.stop_gradient = False
        t.grad = paddle.to_tensor(np.full((4, 4), 2.0, np.float32), dtype=dt)
        params.append(t)
    return params


def test_scaler_unscale_is_one_dispatch_and_lazy():
    from paddle_tpu.optimizer import fused
    params = _scaler_params()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=params)
    sc = paddle.amp.GradScaler(init_loss_scaling=4.0)
    before = fused.dispatch_count()
    sc.unscale_(opt)
    assert fused.dispatch_count() - before == 1, (
        "unscale+check must be ONE fused dispatch, not O(n_params)")
    # verdict not yet resolved (no host sync from unscale_ itself)
    assert sc._pending_finite is not None
    np.testing.assert_allclose(np.asarray(params[1].grad._data),
                               np.full((4, 4), 0.5, np.float32))
    assert sc._found_inf is False       # reading it resolves
    assert sc._pending_finite is None


def test_scaler_detects_inf_and_skips_step():
    params = _scaler_params(8)
    params[3].grad = paddle.to_tensor(
        np.full((4, 4), np.inf, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=params)
    sc = paddle.amp.GradScaler(init_loss_scaling=2.0)
    before = np.asarray(params[0]._data).copy()
    sc.step(opt)
    sc.update()
    assert sc._found_inf is True
    np.testing.assert_array_equal(np.asarray(params[0]._data), before)
    assert sc.get_scale_ratio() == 1.0  # one bad step halves 2.0 -> 1.0


# ---------------------------------------------------------------------------
# MoE: dense runs scan, routed layers stay unrolled
# ---------------------------------------------------------------------------

def test_moe_dense_runs_scan_with_parity():
    from paddle_tpu.models.llama_moe import (
        LlamaMoeForCausalLM, llama_moe_tiny_config)
    cfg_kw = dict(num_hidden_layers=4, moe_layer_interval=3)
    GLOBAL_FLAGS.set("scan_layers", False)
    m1 = LlamaMoeForCausalLM(llama_moe_tiny_config(**cfg_kw))
    GLOBAL_FLAGS.set("scan_layers", True)
    m2 = LlamaMoeForCausalLM(llama_moe_tiny_config(**cfg_kw))
    GLOBAL_FLAGS.set("scan_layers", False)
    stacks = [l for l in m2.model.layers if isinstance(l, LayerStack)]
    assert len(stacks) == 1 and stacks[0].num_layers == 2  # layers 1..2
    sd1 = m1.state_dict()
    assert set(sd1) == set(m2.state_dict())
    missing, unexpected = m2.set_state_dict(sd1)
    assert not missing and not unexpected
    ids = paddle.to_tensor(_ids(vocab=256), dtype="int64")
    _, l1 = m1(ids, labels=ids)
    _, l2 = m2(ids, labels=ids)
    np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# serving/generation bridge keeps working on scanned models
# ---------------------------------------------------------------------------

def test_extract_params_unstacks_scanned_model():
    from paddle_tpu.models.generation import extract_params
    m1 = _build(False)
    m2 = _build(True)
    m2.set_state_dict(m1.state_dict())
    p1 = extract_params(m1)
    p2 = extract_params(m2)
    assert len(p1["layers"]) == len(p2["layers"])
    for l1, l2 in zip(p1["layers"], p2["layers"]):
        for k in l1:
            np.testing.assert_array_equal(np.asarray(l1[k]),
                                          np.asarray(l2[k]), err_msg=k)


# ---------------------------------------------------------------------------
# hapi surface
# ---------------------------------------------------------------------------

def test_hapi_prepare_accumulate_steps():
    class _DS(paddle.io.Dataset):
        def __init__(self, n=32):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((n, 8)).astype(np.float32)
            self.y = rng.standard_normal((n, 1)).astype(np.float32)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    net = paddle.nn.Linear(8, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
                  paddle.nn.MSELoss(), use_jit=True, accumulate_steps=2)
    model.fit(_DS(), batch_size=8, epochs=1, verbose=0)
    assert model._train_step.accumulate_steps == 2
    with pytest.raises(ValueError, match="use_jit"):
        paddle.Model(net).prepare(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters()),
            paddle.nn.MSELoss(), accumulate_steps=2)
