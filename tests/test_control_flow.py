"""Data-dependent control flow (round-3 verdict item 5).

``paddle.static.nn.while_loop`` / ``cond`` / ``case`` / ``switch_case``
are the reference's static control-flow surface
(python/paddle/static/nn/control_flow.py:755); here they lower to
lax.while_loop/cond/switch, so a data-dependent decode loop compiles
ONCE for every trip count (O(1) traces), and the SOT-lite specialization
cache is LRU-bounded.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.static as static


def _t(v, dtype=None):
    return paddle.to_tensor(np.asarray(v), dtype=dtype)


class TestWhileLoop:
    def test_counts_to_limit(self):
        i = _t(0, "int32")
        limit = _t(7, "int32")
        acc = _t(0.0, "float32")

        out_i, out_acc = static.nn.while_loop(
            lambda i, a: i < limit,
            lambda i, a: [i + 1, a + 2.0],
            [i, acc])
        assert int(out_i.numpy()) == 7
        assert float(out_acc.numpy()) == pytest.approx(14.0)

    def test_shape_invariance_enforced(self):
        x = _t(np.zeros((2,), np.float32))
        with pytest.raises(ValueError, match="shape/dtype-invariant"):
            static.nn.while_loop(
                lambda x: paddle.sum(x) < 10,
                lambda x: [paddle.concat([x, x])],
                [x])

    def test_decode_loop_compiles_once(self):
        """A greedy-decode-style loop under to_static: the trip count is
        data-dependent, yet the function traces ONCE and the same
        executable serves every stop position (the O(1)-trace bar)."""
        traces = {"n": 0}
        max_len = 8

        @paddle.jit.to_static
        def decode(logits_row, stop_at):
            traces["n"] += 1   # counts Python traces, not executions

            def cond(i, toks):
                # stop when we emit the stop id or hit the bound
                prev = toks[i]
                return paddle.logical_and(i < max_len - 1, prev != stop_at)

            def body(i, toks):
                nxt = (toks[i] + logits_row[i]).astype("int32")
                toks = paddle.scatter(
                    toks, paddle.to_tensor(np.asarray([0]), "int32") + i + 1,
                    nxt.reshape([1]))
                return [i + 1, toks]

            i0 = paddle.to_tensor(np.asarray(0), "int32")
            toks = paddle.zeros([max_len], "int32")
            i_fin, toks = static.nn.while_loop(cond, body, [i0, toks])
            return toks, i_fin

        rng = np.random.default_rng(0)
        # different rows stop at different steps -> different trip counts
        for stop in (2, 5, 1):
            row = _t(rng.integers(1, 3, (8,)).astype(np.int32))
            toks, steps = decode(row, _t(stop, "int32"))
            assert toks.shape == [8]
        assert traces["n"] == 1, f"expected O(1) traces, got {traces['n']}"

    def test_while_loop_inside_jit_trip_varies(self):
        @paddle.jit.to_static
        def run_until(x, limit):
            def cond(v):
                return paddle.sum(v) < limit

            def body(v):
                return [v * 2.0]

            (out,) = static.nn.while_loop(cond, body, [x])
            return out

        x = _t(np.ones((4,), np.float32))
        a = run_until(x, _t(16.0))
        b = run_until(x, _t(100.0))
        assert float(paddle.sum(a).numpy()) >= 16.0
        assert float(paddle.sum(b).numpy()) >= 100.0


class TestCond:
    def test_eager_concrete(self):
        x = _t(3.0)
        out = static.nn.cond(x > 0, lambda: x * 2, lambda: x - 1)
        assert float(out.numpy()) == pytest.approx(6.0)

    def test_traced_on_device(self):
        @paddle.jit.to_static
        def f(x):
            return static.nn.cond(paddle.sum(x) > 0,
                                  lambda: x * 2.0,
                                  lambda: x - 1.0)

        pos = f(_t(np.ones((3,), np.float32)))
        neg = f(_t(-np.ones((3,), np.float32)))
        np.testing.assert_allclose(pos.numpy(), 2.0)
        np.testing.assert_allclose(neg.numpy(), -2.0)

    def test_case_and_switch(self):
        x = _t(2.0)
        out = static.nn.case(
            [(x < 1, lambda: _t(10.0)), (x < 5, lambda: _t(20.0))],
            default=lambda: _t(30.0))
        assert float(out.numpy()) == pytest.approx(20.0)

        out = static.nn.switch_case(
            _t(1, "int32"),
            {0: lambda: _t(0.0), 1: lambda: _t(11.0), 3: lambda: _t(33.0)})
        assert float(out.numpy()) == pytest.approx(11.0)

        @paddle.jit.to_static
        def g(idx, x):
            return static.nn.switch_case(
                idx, {0: lambda: x + 1.0, 1: lambda: x * 10.0},
                default=lambda: x * 0.0)

        x = _t(np.ones((2,), np.float32))
        np.testing.assert_allclose(g(_t(0, "int32"), x).numpy(), 2.0)
        np.testing.assert_allclose(g(_t(1, "int32"), x).numpy(), 10.0)
        np.testing.assert_allclose(g(_t(9, "int32"), x).numpy(), 0.0)


class TestSpecializationCacheBound:
    def test_lru_eviction(self):
        """k distinct branch paths beyond the bound evict oldest specs
        instead of growing without limit (round-3 verdict weak #5)."""
        from paddle_tpu.core.flags import GLOBAL_FLAGS
        old = GLOBAL_FLAGS.get("sot_specialization_cache_size")
        GLOBAL_FLAGS.set("sot_specialization_cache_size", 3)
        try:
            @paddle.jit.to_static
            def f(x, k):
                # data-dependent if chain: each k takes a different path
                if paddle.sum(x) > k:
                    return x * 2.0
                return x - 1.0

            x = _t(np.full((2,), 5.0, np.float32))
            for k in (0.0, 100.0, 0.0, 100.0):
                f(x, _t(k))
            static_fn = f
            # one guarded entry per signature; specs bounded at 3
            for entry in static_fn._guarded.values():
                assert len(entry["specs"]) <= 3
        finally:
            GLOBAL_FLAGS.set("sot_specialization_cache_size", old)

    def test_loop_site_detection(self):
        """A Python `while bool(t)` loop is detected and reported as a
        loop site during record-mode capture."""
        from paddle_tpu.core import branch_guards as bg
        x = paddle.to_tensor(np.asarray(3.0, np.float32))
        with bg.record() as rec:
            i = paddle.to_tensor(np.asarray(0.0, np.float32))
            while i < x:          # tensor bool, same site each iteration
                i = i + 1.0
        assert len(rec.decisions) == 4          # T T T F
        assert len(rec.loop_sites) == 1
        ((site, count),) = rec.loop_sites.items()
        assert count == 4 and site[0].endswith("test_control_flow.py")


_AUTO_TRACES = 0


class TestAutoWhileRewrite:
    """Round-5 (verdict item 3): a PLAIN Python tensor-dependent while
    loop under to_static compiles once for all trip counts, via the AST
    loop rewrite (jit/loop_rewrite.py) — no explicit
    static.nn.while_loop in user code."""

    def test_plain_python_decode_loop_compiles_once(self):
        global _AUTO_TRACES
        _AUTO_TRACES = 0

        def decode(buf, n):
            global _AUTO_TRACES
            _AUTO_TRACES += 1
            i = paddle.zeros([], "int32")
            state = buf
            while i < n:                       # plain Python while
                state = state * 2.0 + 1.0
                i = i + 1
            return state

        fn = paddle.jit.to_static(decode)
        buf = paddle.to_tensor(np.ones((2, 3), np.float32))

        out3 = fn(buf, paddle.to_tensor(np.int32(3)))
        np.testing.assert_allclose(out3.numpy(), np.ones((2, 3)) * 8 + 7,
                                   rtol=1e-6)
        out5 = fn(buf, paddle.to_tensor(np.int32(5)))
        np.testing.assert_allclose(out5.numpy(), np.ones((2, 3)) * 32 + 31,
                                   rtol=1e-6)
        out0 = fn(buf, paddle.to_tensor(np.int32(0)))
        np.testing.assert_allclose(out0.numpy(), np.ones((2, 3)),
                                   rtol=1e-6)
        # ONE trace covered every trip count: no graph break, no
        # per-trip-count value-guard specialization
        assert _AUTO_TRACES == 1
        assert not fn._graph_broken
        assert not fn._guarded

    def test_rewrite_preserves_python_semantics_eagerly(self):
        from paddle_tpu.jit.loop_rewrite import rewrite_loops

        def collatz_steps(x, n):
            steps = paddle.zeros([], "int32")
            v = x
            while v > 1:
                if int(n) > 0:
                    pass
                v = paddle.where(v % 2 == 0, v // 2, 3 * v + 1)
                steps = steps + 1
            return steps

        # 'pass' inside if is not in the safe subset -> left verbatim
        fn = rewrite_loops(collatz_steps)
        out = fn(paddle.to_tensor(np.int32(6)), paddle.to_tensor(np.int32(1)))
        assert int(out.numpy()) == 8            # 6 3 10 5 16 8 4 2 1

    def test_break_loop_not_rewritten(self):
        from paddle_tpu.jit.loop_rewrite import rewrite_loops

        def f(x):
            while x < 100:
                x = x * 2
                if x > 10:
                    break
            return x

        g = rewrite_loops(f)
        assert not getattr(g, "__ptpu_loop_rewritten__", False)
        assert int(g(paddle.to_tensor(np.int32(3))).numpy()) == 12

    def test_closure_function_rewritten(self):
        from paddle_tpu.jit.loop_rewrite import rewrite_loops
        scale = paddle.to_tensor(np.float32(2.0))

        def f(x, n):
            i = paddle.zeros([], "int32")
            while i < n:
                x = x * scale                  # closure read
                i = i + 1
            return x

        g = rewrite_loops(f)
        assert getattr(g, "__ptpu_loop_rewritten__", False)
        out = g(paddle.to_tensor(np.float32(3.0)),
                paddle.to_tensor(np.int32(4)))
        np.testing.assert_allclose(out.numpy(), 48.0, rtol=1e-6)

    def test_grad_requiring_loop_keeps_tape(self):
        """When gradients flow through the loop state the rewrite must
        NOT reroute to lax.while_loop (non-differentiable): the Python
        loop runs and the tape records."""
        from paddle_tpu.jit.loop_rewrite import rewrite_loops

        def f(w, n):
            i = paddle.zeros([], "int32")
            y = w
            while i < n:
                y = y * 2.0
                i = i + 1
            return y

        g = rewrite_loops(f)
        assert getattr(g, "__ptpu_loop_rewritten__", False)
        w = paddle.to_tensor(np.float32(1.5))
        w.stop_gradient = False
        out = g(w, paddle.to_tensor(np.int32(3)))
        out.backward()
        np.testing.assert_allclose(w.grad.numpy(), 8.0, rtol=1e-6)

    def test_shape_variant_loop_falls_back(self):
        """A growing-buffer loop (concat decode) cannot ride
        lax.while_loop; the rewrite's runtime falls back to the Python
        loop, preserving results."""

        def grow(x, n):
            i = paddle.zeros([], "int32")
            buf = x
            while i < n:
                buf = paddle.concat([buf, x], axis=0)
                i = i + 1
            return buf

        from paddle_tpu.jit.loop_rewrite import rewrite_loops
        g = rewrite_loops(grow)
        assert getattr(g, "__ptpu_loop_rewritten__", False)
        x = paddle.to_tensor(np.ones((1, 2), np.float32))
        out = g(x, paddle.to_tensor(np.int32(3)))
        assert list(out.shape) == [4, 2]

    def test_flag_disables_rewrite(self):
        from paddle_tpu.core.flags import GLOBAL_FLAGS
        from paddle_tpu.jit.loop_rewrite import rewrite_loops

        def f(x, n):
            i = paddle.zeros([], "int32")
            while i < n:
                x = x + 1.0
                i = i + 1
            return x

        old = GLOBAL_FLAGS.get("jit_auto_while")
        try:
            GLOBAL_FLAGS.set("jit_auto_while", False)
            assert rewrite_loops(f) is f
        finally:
            GLOBAL_FLAGS.set("jit_auto_while", old)

    def test_layer_forward_decode_loop(self):
        """A Layer whose forward contains the plain loop compiles once
        through to_static as well."""

        class Decoder(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(4, 4)

            def forward(self, x, n):
                i = paddle.zeros([], "int32")
                h = x
                while i < n:
                    h = paddle.tanh(self.lin(h))
                    i = i + 1
                return h

        m = Decoder()
        m.eval()
        st = paddle.jit.to_static(m)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        o2 = st(x, paddle.to_tensor(np.int32(2)))
        o4 = st(x, paddle.to_tensor(np.int32(4)))
        assert not st.forward._graph_broken and not st.forward._guarded
        # oracle: eager unrolled
        ref = x
        for _ in range(2):
            ref = paddle.tanh(m.lin(ref))
        np.testing.assert_allclose(o2.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-5)
        for _ in range(2):
            ref = paddle.tanh(m.lin(ref))
        np.testing.assert_allclose(o4.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-5)


class TestBoundedDifferentiableWhile:
    """while_loop(maximum_trip_count=N): the reference's while_grad
    capability, TPU-native as a predicated lax.scan — data-dependent trip
    count, gradients flow, records on the tape."""

    def test_matches_unbounded_and_python(self):
        def cond(i, x):
            return i < 5

        def body(i, x):
            return [i + 1, x * 2.0]

        i0 = paddle.zeros([], "int32")
        x0 = paddle.to_tensor(np.float32(1.5))
        i1, x1 = static.nn.while_loop(cond, body, [i0, x0],
                                      maximum_trip_count=16)
        assert int(i1.numpy()) == 5
        np.testing.assert_allclose(x1.numpy(), 1.5 * 32, rtol=1e-6)

    def test_gradient_flows(self):
        """Differentiable tensors ride loop_vars (the reference's while
        block promotes differentiable externals to block inputs)."""
        w = paddle.to_tensor(np.float32(1.1))
        w.stop_gradient = False
        n = paddle.to_tensor(np.int32(3))

        def cond(i, y, w):
            return i < n

        def body(i, y, w):
            return [i + 1, y * w, w]

        i0 = paddle.zeros([], "int32")
        y0 = paddle.to_tensor(np.float32(2.0))
        _, y, _ = static.nn.while_loop(cond, body, [i0, y0, w],
                                       maximum_trip_count=8)
        y.backward()
        # y = 2 * w^3 -> dy/dw = 6 w^2
        np.testing.assert_allclose(w.grad.numpy(), 6 * 1.1 ** 2,
                                   rtol=1e-5)

    def test_under_jit_compiles_once_with_grads(self):
        import paddle_tpu.jit as jit

        def roll(w, n):
            i0 = paddle.zeros([], "int32")

            def cond(i, y):
                return i < n

            def body(i, y):
                return [i + 1, y * w]

            _, y = static.nn.while_loop(
                cond, body, [i0, paddle.ones([], "float32")],
                maximum_trip_count=6)
            return y

        fn = jit.to_static(roll)
        w = paddle.to_tensor(np.float32(2.0))
        out3 = fn(w, paddle.to_tensor(np.int32(3)))
        out5 = fn(w, paddle.to_tensor(np.int32(5)))
        np.testing.assert_allclose(out3.numpy(), 8.0, rtol=1e-6)
        np.testing.assert_allclose(out5.numpy(), 32.0, rtol=1e-6)
        assert not fn._graph_broken and not fn._guarded


def test_auto_while_with_branching_body():
    """A rewritable while whose body contains if/elif/else assignment
    chains still compiles once (the safe-subset If support)."""
    import paddle_tpu.jit as jit
    global _AUTO_TRACES

    def stepper(x, n):
        i = paddle.zeros([], "int32")
        y = x
        while i < n:
            half = y * 0.5
            if True:
                y = half + 1.0
            else:
                y = half
            i = i + 1
        return y

    from paddle_tpu.jit.loop_rewrite import rewrite_loops
    g = rewrite_loops(stepper)
    assert getattr(g, "__ptpu_loop_rewritten__", False)
    fn = jit.to_static(stepper)
    x = paddle.to_tensor(np.float32(8.0))
    out2 = fn(x, paddle.to_tensor(np.int32(2)))
    out4 = fn(x, paddle.to_tensor(np.int32(4)))
    np.testing.assert_allclose(out2.numpy(), 8 * 0.25 + 0.5 + 1, rtol=1e-6)
    assert np.isfinite(out4.numpy())
    assert not fn._graph_broken and not fn._guarded


def test_auto_while_temp_read_after_loop_stays_correct():
    """A body temporary read AFTER the loop keeps exact Python
    semantics (it is loop-carried, or the rewrite falls back)."""
    from paddle_tpu.jit.loop_rewrite import rewrite_loops

    def f(x, n):
        i = paddle.zeros([], "int32")
        last = x * 0.0
        while i < n:
            last = x + i.astype("float32")
            i = i + 1
        return last                       # value from the FINAL trip

    g = rewrite_loops(f)
    with paddle.no_grad():
        out = g(paddle.to_tensor(np.float32(10.0)),
                paddle.to_tensor(np.int32(3)))
    np.testing.assert_allclose(out.numpy(), 12.0, rtol=1e-6)
