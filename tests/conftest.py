"""Test configuration: run everything on an 8-device virtual CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): "multi-node" testing is
multi-device single-host; the XLA-CPU 8-device stand-in plays the role the
reference gives loopback NCCL.
"""
import os

# Axon claim discipline: tests are CPU-only; make absolutely sure no axon
# backend is ever initialized from a test process (a claim through the
# relay would serialize against — and can wedge — the single TPU pool).
# sitecustomize has already imported jax by now, so the env var alone
# doesn't stop registration, but jax.config platforms=cpu below prevents
# backend init; clearing the var also covers worker subprocesses spawned
# by tests (launch CLI tests re-exec python).
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"

# XLA parses XLA_FLAGS at backend-creation time, so setting it here works even
# though sitecustomize already imported jax at interpreter startup.
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

import jax  # noqa: E402

# sitecustomize (axon TPU plugin) imports jax before conftest runs, so the
# JAX_PLATFORMS env var is already baked in — override via config instead.
# Backends are created lazily, so this lands before any device is claimed.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Numeric tests compare against float32 numpy; the default matmul precision on
# this stack is TPU-like (bf16 passes), so pin highest precision for testing.
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compilation cache: repeated suite runs skip recompiles (the
# analog of the reference's build-cache CI tier, tools/parallel_UT_rule.py).
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("PADDLE_TPU_TEST_CACHE",
                                     "/tmp/paddle_tpu_jax_test_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # older jax without the knobs
    pass


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield
