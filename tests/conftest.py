"""Test configuration: run everything on an 8-device virtual CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): "multi-node" testing is
multi-device single-host; the XLA-CPU 8-device stand-in plays the role the
reference gives loopback NCCL.
"""
import os

# Must be set before jax initializes.
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8 " + os.environ.get("XLA_FLAGS", ""))
os.environ["JAX_PLATFORMS"] = "cpu"
# Tests never touch the TPU: clearing PALLAS_AXON_POOL_IPS would skip the axon
# plugin claim, but sitecustomize has already run by the time conftest loads —
# so invoke pytest as:  PALLAS_AXON_POOL_IPS= python -m pytest tests/ -q
# (see .claude/skills/verify/SKILL.md).

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Numeric tests compare against float32 numpy; the default matmul precision on
# this stack is TPU-like (bf16 passes), so pin highest precision for testing.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield
