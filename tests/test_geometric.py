"""paddle.geometric parity tests.

Oracles are the reference's own docstring examples
(python/paddle/geometric/message_passing/send_recv.py:79-101,240-260,
442-460; reindex.py:51-55; math.py examples) plus numpy re-derivations.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


def I(x):
    return paddle.to_tensor(np.asarray(x, np.int64))


class TestSendRecv:
    X = [[0.0, 2.0, 3.0], [1.0, 4.0, 5.0], [2.0, 6.0, 7.0]]
    SRC = [0, 1, 2, 0]
    DST = [1, 2, 1, 0]

    def test_send_u_recv_sum(self):
        # reference example (send_recv.py:79): out = [[0,2,3],[2,8,10],[1,4,5]]
        out = G.send_u_recv(T(self.X), I(self.SRC), I(self.DST), "sum")
        np.testing.assert_allclose(
            out.numpy(), [[0, 2, 3], [2, 8, 10], [1, 4, 5]])

    def test_send_u_recv_mean_max_min(self):
        x, s, d = T(self.X), I(self.SRC), I(self.DST)
        np.testing.assert_allclose(
            G.send_u_recv(x, s, d, "mean").numpy(),
            [[0, 2, 3], [1, 4, 5], [1, 4, 5]])
        np.testing.assert_allclose(
            G.send_u_recv(x, s, d, "max").numpy(),
            [[0, 2, 3], [2, 6, 7], [1, 4, 5]])
        np.testing.assert_allclose(
            G.send_u_recv(x, s, d, "min").numpy(),
            [[0, 2, 3], [0, 2, 3], [1, 4, 5]])

    def test_out_size_pads_and_truncates(self):
        # reference: out_size >= max(dst)+1 zero-pads extra rows
        out = G.send_u_recv(T(self.X), I(self.SRC), I(self.DST), "sum",
                            out_size=5)
        assert tuple(out.shape) == (5, 3)
        np.testing.assert_allclose(out.numpy()[3:], 0)
        # max-reduce with out_size: empty rows are 0, not -inf
        out = G.send_u_recv(T(self.X), I(self.SRC), I(self.DST), "max",
                            out_size=5)
        np.testing.assert_allclose(out.numpy()[3:], 0)

    def test_send_ue_recv(self):
        # reference example (send_recv.py:240): y = [1,1,1,1] broadcasts,
        # add then sum-reduce: out = [[1,3,4],[4,10,12],[2,5,6]]
        y = T([1.0, 1.0, 1.0, 1.0]).reshape([4, 1])
        out = G.send_ue_recv(T(self.X), y, I(self.SRC), I(self.DST),
                             "add", "sum")
        np.testing.assert_allclose(
            out.numpy(), [[1, 3, 4], [4, 10, 12], [2, 5, 6]])

    def test_send_uv(self):
        # x[src] + y[dst] per edge
        x = T(self.X)
        y = T([[1.0, 1.0, 1.0]] * 3)
        out = G.send_uv(x, y, I(self.SRC), I(self.DST), "add")
        ref = np.asarray(self.X)[self.SRC] + np.asarray(y.numpy())[self.DST]
        np.testing.assert_allclose(out.numpy(), ref)

    def test_send_u_recv_grad(self):
        x = T(self.X)
        x.stop_gradient = False
        out = G.send_u_recv(x, I(self.SRC), I(self.DST), "sum")
        out.sum().backward()
        # node 0 feeds 2 edges, others 1
        np.testing.assert_allclose(x.grad.numpy()[:, 0], [2, 1, 1])

    def test_jit_with_static_out_size(self):
        import paddle_tpu.jit as jit

        @jit.to_static
        def f(x, s, d):
            return G.send_u_recv(x, s, d, "sum", out_size=3)

        out = f(T(self.X), I(self.SRC), I(self.DST))
        np.testing.assert_allclose(
            out.numpy(), [[0, 2, 3], [2, 8, 10], [1, 4, 5]])


class TestSegment:
    def test_segment_ops(self):
        data = T([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0], [4.0, 5.0, 6.0]])
        ids = I([0, 0, 1])
        np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                                   [[4, 4, 4], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                                   [[2, 2, 2], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                                   [[1, 2, 1], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                                   [[3, 2, 3], [4, 5, 6]])

    def test_segment_grad(self):
        data = T([[1.0], [2.0], [3.0]])
        data.stop_gradient = False
        G.segment_sum(data, I([0, 1, 1])).sum().backward()
        np.testing.assert_allclose(data.grad.numpy().ravel(), [1, 1, 1])


class TestReindex:
    def test_reindex_graph(self):
        # reference example (reindex.py:51-55)
        x = I([0, 1, 2])
        neighbors = I([8, 9, 0, 4, 7, 6, 7])
        count = paddle.to_tensor(np.asarray([2, 3, 2], np.int32))
        src, dst, out_nodes = G.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(out_nodes.numpy(),
                                      [0, 1, 2, 8, 9, 4, 7, 6])

    def test_reindex_heter_graph(self):
        x = I([0, 1, 2])
        n1, c1 = I([8, 9, 0, 4, 7, 6, 7]), I([2, 3, 2])
        n2, c2 = I([0, 2, 3, 5, 1]), I([1, 3, 1])
        srcs, dsts, out_nodes = G.reindex_heter_graph(x, [n1, n2], [c1, c2])
        assert len(srcs) == 2 and len(dsts) == 2
        # shared id space: node 0/2 map to their input slots
        np.testing.assert_array_equal(srcs[1].numpy()[:2], [0, 2])


class TestSampling:
    def _csc(self):
        # 4 nodes; in-neighbors: 0<-{1,2,3}, 1<-{0}, 2<-{0,1}, 3<-{}
        row = I([1, 2, 3, 0, 0, 1])
        colptr = I([0, 3, 4, 6, 6])
        return row, colptr

    def test_full_neighborhood(self):
        row, colptr = self._csc()
        nbr, cnt = G.sample_neighbors(row, colptr, I([0, 2, 3]),
                                      sample_size=-1)
        np.testing.assert_array_equal(cnt.numpy(), [3, 2, 0])
        np.testing.assert_array_equal(nbr.numpy(), [1, 2, 3, 0, 1])

    def test_sampled_subset_and_determinism(self):
        row, colptr = self._csc()
        paddle.seed(7)
        nbr1, cnt1 = G.sample_neighbors(row, colptr, I([0]), sample_size=2)
        assert cnt1.numpy()[0] == 2
        assert set(np.asarray(nbr1.numpy())) <= {1, 2, 3}
        paddle.seed(7)
        nbr2, _ = G.sample_neighbors(row, colptr, I([0]), sample_size=2)
        np.testing.assert_array_equal(nbr1.numpy(), nbr2.numpy())

    def test_eids_and_weighted(self):
        row, colptr = self._csc()
        eids = I([10, 11, 12, 13, 14, 15])
        nbr, cnt, oe = G.sample_neighbors(row, colptr, I([1, 2]),
                                          sample_size=-1, eids=eids,
                                          return_eids=True)
        np.testing.assert_array_equal(oe.numpy(), [13, 14, 15])
        w = T([0.0, 0.0, 1.0, 1.0, 1.0, 1.0])
        paddle.seed(0)
        nbr, cnt = G.weighted_sample_neighbors(row, colptr, w, I([0]),
                                               sample_size=1)
        # weights zero out neighbors 1 and 2 of node 0 -> must pick 3
        np.testing.assert_array_equal(nbr.numpy(), [3])


@pytest.mark.slow
def test_gcn_trains():
    """A 2-layer GCN over send_u_recv(mean) learns a toy 2-community node
    classification — the end-to-end proof the subsystem composes with
    nn/optimizer/autograd."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    n, d = 20, 8
    # two communities with dense intra-community edges + self loops
    edges = [(i, j) for i in range(10) for j in range(10) if i != j]
    edges += [(i, j) for i in range(10, 20) for j in range(10, 20) if i != j]
    edges += [(i, i) for i in range(n)]
    src = I([e[0] for e in edges])
    dst = I([e[1] for e in edges])
    x = T(rng.standard_normal((n, d)))
    labels = paddle.to_tensor(np.asarray([0] * 10 + [1] * 10, np.int64))

    class GCNLayer(nn.Layer):
        def __init__(self, din, dout):
            super().__init__()
            self.lin = nn.Linear(din, dout)

        def forward(self, h):
            return G.send_u_recv(self.lin(h), src, dst, "mean", out_size=n)

    class GCN(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = GCNLayer(d, 16)
            self.l2 = GCNLayer(16, 2)

        def forward(self, h):
            return self.l2(paddle.nn.functional.relu(self.l1(h)))

    paddle.seed(0)
    model = GCN()
    opt = paddle.optimizer.Adam(learning_rate=5e-2,
                                parameters=model.parameters())
    losses = []
    for _ in range(30):
        loss = F.cross_entropy(model(x), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.1 * losses[0], losses
    pred = np.argmax(np.asarray(model(x).numpy()), -1)
    assert (pred == np.asarray(labels.numpy())).mean() == 1.0


class TestKhopSampler:
    def test_two_hop_structure(self):
        # graph from the reference docstring
        row = I([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7])
        colptr = I([0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13])
        nodes = I([0, 8, 1, 2])
        paddle.seed(0)
        src, dst, sample_index, reindex = G.graph_khop_sampler(
            row, colptr, nodes, [2, 2])
        si = np.asarray(sample_index.numpy())
        # input nodes lead the id space, in order
        np.testing.assert_array_equal(si[:4], [0, 8, 1, 2])
        np.testing.assert_array_equal(np.asarray(reindex.numpy()),
                                      [0, 1, 2, 3])
        s = np.asarray(src.numpy()).ravel()
        d = np.asarray(dst.numpy()).ravel()
        assert len(s) == len(d) > 0
        # every edge is a REAL edge of the graph under the reindex map
        rown = np.asarray(row.numpy())
        cp = np.asarray(colptr.numpy())
        for a, b in zip(s, d):
            src_orig, dst_orig = si[a], si[b]
            neigh = rown[cp[dst_orig]:cp[dst_orig + 1]]
            assert src_orig in neigh, (src_orig, dst_orig)

    def test_eids(self):
        row = I([1, 2, 0])
        colptr = I([0, 2, 3, 3])
        eids = I([10, 11, 12])
        src, dst, si, re, ee = G.graph_khop_sampler(
            row, colptr, I([0]), [2], sorted_eids=eids, return_eids=True)
        got = sorted(np.asarray(ee.numpy()).ravel().tolist())
        assert got == [10, 11]
