"""audio features, text utilities, device API."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import (Spectrogram, MelSpectrogram, LogMelSpectrogram,
                              MFCC, stft, compute_fbank_matrix)
from paddle_tpu.text import Vocab, ViterbiDecoder


def test_stft_parseval_and_shapes():
    t = np.linspace(0, 1, 16000, dtype=np.float32)
    sig = np.sin(2 * np.pi * 440 * t)
    x = paddle.to_tensor(sig[None])
    spec = stft(x, n_fft=512, hop_length=128)
    assert spec.shape[1] == 257  # n_fft//2+1 bins
    mag = Spectrogram(n_fft=512, hop_length=128)(x)
    # 440 Hz -> bin ~14: dominant bin
    m = mag.numpy()[0]
    assert abs(int(m.mean(-1).argmax()) - round(440 * 512 / 16000)) <= 1


def test_mel_pipeline():
    x = paddle.to_tensor(np.random.randn(2, 8000).astype(np.float32))
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert mel.shape[0] == 2 and mel.shape[1] == 40
    logmel = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
    assert mfcc.shape[1] == 13
    fb = compute_fbank_matrix(16000, 512, 40)
    assert fb.shape == (40, 257) and fb.sum(1).min() > 0


def test_vocab_and_dataset(tmp_path):
    p = tmp_path / "data.tsv"
    p.write_text("pos\tgood movie great\nneg\tbad terrible movie\n")
    from paddle_tpu.text import TextFileDataset
    ds = TextFileDataset(str(p), max_len=4)
    ids, label = ds[0]
    assert ids.shape == (4,) and label in (0, 1)
    v = ds.vocab
    assert v["movie"] != v.unk_index
    assert v.to_tokens(v.to_ids(["movie"])) == ["movie"]
    assert v["zzz_unknown"] == v.unk_index


def test_viterbi_decode_simple():
    # 2 tags; transitions force alternation
    trans = np.array([[-10.0, 0.0], [0.0, -10.0]], np.float32)
    emissions = np.zeros((1, 4, 2), np.float32)
    emissions[0, 0, 0] = 5.0  # start in tag 0
    dec = ViterbiDecoder(paddle.to_tensor(trans))
    scores, path = dec(paddle.to_tensor(emissions),
                       paddle.to_tensor(np.array([4])))
    assert list(path.numpy()[0]) == [0, 1, 0, 1]


def test_device_streams_events():
    import paddle_tpu.device as device
    assert device.device_count() >= 1
    e1, e2 = device.Event(), device.Event()
    e1.record()
    x = paddle.to_tensor(np.random.randn(64, 64).astype(np.float32))
    y = paddle.matmul(x, x)
    e2.record()
    dt = e1.elapsed_time(e2)
    assert dt >= 0
    s = device.current_stream()
    s.synchronize()
    with device.stream_guard(device.Stream()):
        _ = paddle.matmul(x, x)
    assert device.cuda.memory_allocated() >= 0


def test_viterbi_respects_lengths():
    trans = np.array([[-10.0, 0.0], [0.0, -10.0]], np.float32)
    em = np.zeros((2, 6, 2), np.float32)
    em[:, 0, 0] = 5.0
    # sequence 1 has huge emissions in the padding region that would flip
    # the path if (wrongly) decoded
    em[1, 3:, 1] = 100.0
    dec = ViterbiDecoder(paddle.to_tensor(trans))
    _, full = dec(paddle.to_tensor(em), paddle.to_tensor(np.array([6, 3])))
    assert list(full.numpy()[1][:3]) == [0, 1, 0]  # within true length
    # frozen tail repeats the final tag instead of chasing padding
    assert all(t == full.numpy()[1][2] for t in full.numpy()[1][3:])


def test_vlog_tiering(capsys, caplog):
    import logging

    import paddle_tpu as paddle
    from paddle_tpu.core.vlog import vlog, vlog_is_on

    paddle.set_flags({"FLAGS_v": 0})
    assert not vlog_is_on(1)
    with caplog.at_level(logging.DEBUG, logger="paddle_tpu"):
        vlog(1, "hidden %d", 1)
        assert not caplog.records
        paddle.set_flags({"FLAGS_v": 3})
        assert vlog_is_on(3) and not vlog_is_on(4)
        vlog(3, "visible %s", "msg", component="collective")
        assert any("V3 visible msg" in r.message for r in caplog.records)
        assert any(r.name == "paddle_tpu.collective"
                   for r in caplog.records)
    paddle.set_flags({"FLAGS_v": 0})


def test_device_memory_stats_surface():
    import paddle_tpu.device as D

    stats = D.memory_stats()
    # CPU backend publishes no stats -> None; a real chip returns a dict
    assert stats is None or "bytes_in_use" in stats
    assert isinstance(D.memory_allocated(), int)
    assert isinstance(D.max_memory_allocated(), int)


def test_audio_functional_reference_names():
    """paddle.audio.functional public helpers (reference:
    audio/functional/functional.py): slaney mel scale round-trip,
    filterbank shape, dB conversion, ortho DCT."""
    import numpy as np
    import paddle_tpu.audio.functional as AF

    # scalar round-trip on both scales
    for htk in (False, True):
        hz = 440.0
        mel = AF.hz_to_mel(hz, htk=htk)
        back = AF.mel_to_hz(mel, htk=htk)
        assert abs(back - hz) < 1e-6, (htk, back)

    freqs = AF.mel_frequencies(n_mels=10, f_min=0.0, f_max=8000.0)
    f = np.asarray(freqs.numpy())
    assert f.shape == (10,) and f[0] == 0.0 and np.all(np.diff(f) > 0)

    ff = np.asarray(AF.fft_frequencies(sr=16000, n_fft=512).numpy())
    assert ff.shape == (257,) and ff[-1] == 8000.0

    fb = AF.compute_fbank_matrix(16000, 512, n_mels=40)
    assert tuple(fb.shape) == (40, 257)
    # htk vs slaney scales place centers differently; norm changes peaks
    fb_htk = np.asarray(AF.compute_fbank_matrix(
        16000, 512, n_mels=40, htk=True).numpy())
    assert not np.allclose(np.asarray(fb.numpy()), fb_htk)
    fb_nonorm = np.asarray(AF.compute_fbank_matrix(
        16000, 512, n_mels=40, norm=None).numpy())
    assert np.isclose(fb_nonorm.max(), 1.0, atol=1e-2)   # ~unit peaks (grid)
    assert np.asarray(fb.numpy()).max() < 1.0             # area-normed

    db = AF.power_to_db(np.asarray([1.0, 0.1, 1e-12]), top_db=80.0)
    d = np.asarray(db.numpy())
    assert abs(d[0] - 0.0) < 1e-5 and abs(d[1] + 10.0) < 1e-4

    dct = np.asarray(AF.create_dct(13, 40).numpy())
    assert dct.shape == (40, 13)
    # ortho: columns are orthonormal
    gram = dct.T @ dct
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-6)

    w = np.asarray(AF.get_window("hann", 400).numpy())
    assert w.shape == (400,) and w[0] == 0.0
