"""HLO fusion forensics gates (ISSUE 12, ROADMAP item 4b): fusion as a
measured, gated property — the parser, the two capture surfaces
(TrainStep / ragged serving step), and the injected defusion regression
(FLAGS_fusion_probe_barrier) that proves the proxy gates fire."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import jit as pjit
from paddle_tpu.core.flags import GLOBAL_FLAGS
from paddle_tpu.jit.hlo_forensics import fusion_stats, shape_bytes
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import LLMEngine


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


# ---------------------------------------------------------------------------
# parser unit gates (synthetic HLO text — exact expectations)
# ---------------------------------------------------------------------------

_SYNTH = """\
HloModule jit_f, entry_computation_layout={(f32[8,16]{1,0})->f32[8,16]{1,0}}

%fused_computation (p0: f32[8,16]) -> f32[8,16] {
  %p0 = f32[8,16]{1,0} parameter(0)
  ROOT %e = f32[8,16]{1,0} exponential(f32[8,16]{1,0} %p0)
}

%wbody (carry: (s32[], f32[4])) -> (s32[], f32[4]) {
  %carry = (s32[], f32[4]{0}) parameter(0)
  %g = s32[] get-tuple-element((s32[], f32[4]{0}) %carry), index=0
  %h = f32[4]{0} get-tuple-element((s32[], f32[4]{0}) %carry), index=1
  %inner = f32[4]{0} fusion(f32[4]{0} %h), kind=kInput, calls=%fc2
  ROOT %t = (s32[], f32[4]{0}) tuple(s32[] %g, f32[4]{0} %inner)
}

ENTRY %main (Arg_0.1: f32[8,16]) {
  %Arg_0.1 = f32[8,16]{1,0} parameter(0)
  %c = f32[] constant(1)
  %b = f32[8,16]{1,0} broadcast(f32[] %c), dimensions={}
  %fusion = f32[8,16]{1,0} fusion(f32[8,16]{1,0} %Arg_0.1), kind=kLoop, calls=%fused_computation
  %d = f32[8,16]{1,0} dot(f32[8,16]{1,0} %fusion, f32[8,16]{1,0} %b)
  %gte = f32[8,16]{1,0} bitcast(f32[8,16]{1,0} %d)
  ROOT %add = f32[8,16]{1,0} add(f32[8,16]{1,0} %gte, f32[8,16]{1,0} %b)
}
"""


def test_shape_bytes_exact():
    assert shape_bytes("f32[8,16]{1,0}") == 8 * 16 * 4
    assert shape_bytes("bf16[4] s8[2,3]") == 4 * 2 + 6
    assert shape_bytes("s32[]") == 4                 # scalar
    assert shape_bytes("pred[7]") == 7
    assert shape_bytes("no shapes here") == 0


def test_fusion_stats_on_synthetic_module():
    s = fusion_stats(_SYNTH)
    # module-wide fusions: the entry kLoop + the while-body kInput
    assert s["fusion_count"] == 2
    assert s["fusion_kinds"] == {"kInput": 1, "kLoop": 1}
    # entry kernels: broadcast + fusion + dot + add (parameter/constant/
    # bitcast are free); instructions counts every def
    assert s["kernel_count"] == 4
    assert s["entry_instruction_count"] == 7
    # entry fusion line: result + 1 operand, both f32[8,16] = 512 B;
    # while-body fusion: f32[4] x 2 = 32 B
    assert s["fusion_bytes_total"] == 2 * 512 + 2 * 16
    assert s["fusion_bytes_max"] == 1024


def test_fusion_stats_empty_module():
    s = fusion_stats("HloModule m\n\nENTRY %main () {\n}\n")
    assert s["fusion_count"] == 0
    assert s["kernel_count"] == 0
    assert s["fusion_bytes_max"] == 0


# ---------------------------------------------------------------------------
# capture surfaces
# ---------------------------------------------------------------------------

def _train_step(model, capture_hlo):
    cfg = model.config
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def loss_fn(ids):
        logits = model(ids)
        return F.cross_entropy(
            logits[:, :-1].reshape((-1, cfg.vocab_size)),
            ids[:, 1:].reshape((-1,)))

    step = pjit.TrainStep(model, loss_fn, opt, capture_hlo=capture_hlo)
    ids = paddle.to_tensor(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)))
    step(ids)
    return step


def test_trainstep_capture_hlo_opt_in(tiny_model):
    """capture_hlo=True keeps the optimized module text of an UNSHARDED
    compile (the fusion probe's surface); the default stays None — the
    extra lower+compile is opt-in."""
    step = _train_step(tiny_model, capture_hlo=True)
    assert step.last_hlo_text is not None
    stats = fusion_stats(step.last_hlo_text)
    assert stats["fusion_count"] > 0
    assert stats["kernel_count"] > 0
    step_off = _train_step(tiny_model, capture_hlo=False)
    assert step_off.last_hlo_text is None


def test_ragged_step_hlo_is_out_of_band(tiny_model):
    """The engine's AOT HLO capture measures the REAL serving
    executable without perturbing the dispatch path: fusion stats come
    back, and the trace-count gate still reads whatever it read
    before."""
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, max_num_seqs=2)
    before = eng.decode_cache_size()
    hlo = eng.ragged_step_hlo()
    assert "ragged_step" in hlo
    stats = fusion_stats(hlo)
    assert stats["fusion_count"] > 0
    assert stats["fusion_bytes_total"] > 0
    assert eng.decode_cache_size() == before, \
        "AOT lowering must not touch the jit dispatch cache"
    # the engine still serves normally afterwards
    eng.add_request([1, 2, 3], max_new_tokens=2)
    eng.run(max_steps=50)
    assert eng.decode_cache_size() == 1


def test_fusion_barrier_flag_splits_the_region(tiny_model):
    """FLAGS_fusion_probe_barrier is the injected regression: the
    barrier splits the ragged layer's hot fused region, so fusion AND
    kernel counts rise and bytes-touched grows — exactly what the
    proxy-bench gates pin."""
    def stats():
        eng = LLMEngine(tiny_model, max_len=32, page_size=4,
                        max_num_seqs=2)
        return fusion_stats(eng.ragged_step_hlo())

    base = stats()
    GLOBAL_FLAGS.set("fusion_probe_barrier", True)
    try:
        split = stats()
    finally:
        GLOBAL_FLAGS.set("fusion_probe_barrier", False)
    assert split["fusion_count"] > base["fusion_count"]
    assert split["kernel_count"] > base["kernel_count"]
    assert split["fusion_bytes_total"] > base["fusion_bytes_total"]


# ---------------------------------------------------------------------------
# launch accounting (ISSUE 18): launches_per_token over unoptimized
# lowerings
# ---------------------------------------------------------------------------

def _program(markers):
    lines = ["module @jit_step {"]
    lines += ['  %x = "stablehlo.rsqrt"(%a) : (f32) -> f32'] * markers
    lines += ['  %y = "stablehlo.add"(%a, %b) : (f32, f32) -> f32',
              "}"]
    return "\n".join(lines)


def test_launch_stats_unrolled_vs_collapsed():
    from paddle_tpu.jit.hlo_forensics import launch_stats
    # unrolled: L=4 bodies x 2 markers + 1 final-norm marker
    s = launch_stats(_program(9), num_layers=4)
    assert s["marker_count"] == 9
    assert s["layer_body_sites"] == 4
    assert s["launches_per_token"] == 4.0
    assert not s["collapsed"]
    # scanned: ONE body site regardless of depth
    s = launch_stats(_program(3), num_layers=4)
    assert s["layer_body_sites"] == 1
    assert s["launches_per_token"] == 1.0
    assert s["collapsed"]


def test_launch_stats_burst_amortization():
    from paddle_tpu.jit.hlo_forensics import launch_stats
    s = launch_stats(_program(3), num_layers=4, tokens_per_invocation=8)
    assert s["launches_per_token"] == 0.125
    assert s["collapsed"]
    # the int8 burst body carries an extra pre-append prologue norm
    s = launch_stats(_program(4), num_layers=4, markers_per_body=3,
                     tokens_per_invocation=8)
    assert s["layer_body_sites"] == 1 and s["launches_per_token"] == 0.125


def test_launch_stats_refuses_to_fabricate():
    """A marker count inconsistent with the constants means the traced
    body changed — mis-dividing would fabricate a launch count."""
    import pytest
    from paddle_tpu.jit.hlo_forensics import launch_stats
    with pytest.raises(ValueError, match="do not decompose"):
        launch_stats(_program(4), num_layers=4)        # (4-1) % 2 != 0
    with pytest.raises(ValueError, match="do not decompose"):
        launch_stats(_program(0), num_layers=4)        # fewer than overhead


def test_engine_lowering_matches_marker_model(tiny_model):
    """The marker constants against the REAL engine lowerings: fp
    ragged body carries exactly 2 rsqrt sites per layer + 1 final norm,
    and the model-scope scan collapses the per-layer sites to one."""
    import re
    from paddle_tpu.serving import LLMEngine
    eng = LLMEngine(tiny_model, max_len=32, page_size=4)
    n_markers = len(re.findall(r"\brsqrt\b", eng.ragged_step_lowering()))
    L = tiny_model.config.num_hidden_layers
    assert n_markers == 2 * L + 1
    s = eng.launch_stats()
    assert s["layer_body_sites"] == L
