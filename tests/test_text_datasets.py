"""paddle.text datasets over local files in the upstream formats
(reference: text/datasets/{uci_housing,imikolov,imdb}.py; zero-egress
environment, so the loaders parse caller-provided files).
"""
import io
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.text import UCIHousing, Imikolov, Imdb


def test_uci_housing_split_and_normalization(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.uniform(1, 10, (20, 14))
    path = tmp_path / "housing.data"
    path.write_text(" ".join(f"{v:.4f}" for v in data.reshape(-1)))
    train = UCIHousing(data_file=str(path), mode="train")
    test = UCIHousing(data_file=str(path), mode="test")
    assert len(train) == 16 and len(test) == 4      # 80/20 split
    feat, price = train[0]
    assert feat.shape == (13,) and price.shape == (1,)
    # price column is NOT normalized (reference behavior)
    np.testing.assert_allclose(float(price[0]), data[0, -1], rtol=1e-4)
    # features are train-stat normalized: reconstruct one
    offset = 16
    avg = data[:offset, 0].mean()
    span = data[:offset, 0].max() - data[:offset, 0].min()
    np.testing.assert_allclose(float(feat[0]),
                               (data[0, 0] - avg) / span, rtol=1e-4)


def _ptb_tar(tmp_path, train_lines, valid_lines):
    path = tmp_path / "simple-examples.tgz"
    with tarfile.open(path, "w:gz") as tf:
        for name, lines in (("simple-examples/data/ptb.train.txt",
                             train_lines),
                            ("simple-examples/data/ptb.valid.txt",
                             valid_lines)):
            blob = ("\n".join(lines) + "\n").encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return str(path)


def test_imikolov_ngram_and_seq(tmp_path):
    train = ["the cat sat on the mat"] * 3 + ["a cat ran"] * 3
    valid = ["the cat ran"]
    path = _ptb_tar(tmp_path, train, valid)
    ds = Imikolov(data_file=path, data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=3)
    # dict: words with freq >= 3 (the, cat, sat?, on?, mat? appear 3x via
    # repetition; 'a'/'ran' 3x too) + <unk>
    assert "<unk>" in ds.word_idx and "cat" in ds.word_idx
    first = ds[0]
    assert len(first) == 2          # window-size tuples
    seq = Imikolov(data_file=path, data_type="SEQ", mode="test",
                   min_word_freq=3)
    src, tgt = seq[0]
    assert len(src) == len(tgt)     # shifted pair


def _imdb_tar(tmp_path):
    path = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0_9.txt": b"a great great movie",
        "aclImdb/train/neg/0_1.txt": b"a terrible movie",
        "aclImdb/test/pos/0_8.txt": b"great fun",
        "aclImdb/test/neg/0_2.txt": b"terrible bore",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, blob in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return str(path)


def test_imdb_labels_and_vocab(tmp_path):
    path = _imdb_tar(tmp_path)
    train = Imdb(data_file=path, mode="train", cutoff=10)
    assert len(train) == 2
    ids0, label0 = train[0]
    ids1, label1 = train[1]
    assert label0 == 0 and label1 == 1      # pos=0, neg=1 (reference)
    # 'great' appears twice in train -> ranked ahead of singletons
    assert train.word_idx["great"] < train.word_idx["terrible"]
    test = Imdb(data_file=path, mode="test", cutoff=10)
    assert len(test) == 2


def test_download_disabled_raises():
    with pytest.raises(RuntimeError, match="zero egress"):
        UCIHousing()
    with pytest.raises(RuntimeError, match="zero egress"):
        Imdb()


def test_imdb_external_word_idx(tmp_path):
    # the legacy dataset.imdb.train(word_dict) contract: samples encode
    # with the CALLER's vocabulary, not a rebuilt one
    path = _imdb_tar(tmp_path)
    custom = {"great": 0, "movie": 1}
    ds = Imdb(data_file=path, mode="train", word_idx=custom)
    assert ds.word_idx["<unk>"] == 2
    ids0, _ = ds[0]  # "a great great movie" -> unk, 0, 0, 1
    assert list(ids0) == [2, 0, 0, 1]

    import paddle_tpu as paddle
    reader = paddle.dataset.imdb.train(custom, data_file=path)
    ids, label = next(iter(reader()))
    assert list(ids) == [2, 0, 0, 1] and label == 0
