"""paddle.text datasets over local files in the upstream formats
(reference: text/datasets/{uci_housing,imikolov,imdb}.py; zero-egress
environment, so the loaders parse caller-provided files).
"""
import io
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.text import UCIHousing, Imikolov, Imdb


def test_uci_housing_split_and_normalization(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.uniform(1, 10, (20, 14))
    path = tmp_path / "housing.data"
    path.write_text(" ".join(f"{v:.4f}" for v in data.reshape(-1)))
    train = UCIHousing(data_file=str(path), mode="train")
    test = UCIHousing(data_file=str(path), mode="test")
    assert len(train) == 16 and len(test) == 4      # 80/20 split
    feat, price = train[0]
    assert feat.shape == (13,) and price.shape == (1,)
    # price column is NOT normalized (reference behavior)
    np.testing.assert_allclose(float(price[0]), data[0, -1], rtol=1e-4)
    # features are train-stat normalized: reconstruct one
    offset = 16
    avg = data[:offset, 0].mean()
    span = data[:offset, 0].max() - data[:offset, 0].min()
    np.testing.assert_allclose(float(feat[0]),
                               (data[0, 0] - avg) / span, rtol=1e-4)


def _ptb_tar(tmp_path, train_lines, valid_lines):
    path = tmp_path / "simple-examples.tgz"
    with tarfile.open(path, "w:gz") as tf:
        for name, lines in (("simple-examples/data/ptb.train.txt",
                             train_lines),
                            ("simple-examples/data/ptb.valid.txt",
                             valid_lines)):
            blob = ("\n".join(lines) + "\n").encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return str(path)


def test_imikolov_ngram_and_seq(tmp_path):
    train = ["the cat sat on the mat"] * 3 + ["a cat ran"] * 3
    valid = ["the cat ran"]
    path = _ptb_tar(tmp_path, train, valid)
    ds = Imikolov(data_file=path, data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=3)
    # dict: words with freq >= 3 (the, cat, sat?, on?, mat? appear 3x via
    # repetition; 'a'/'ran' 3x too) + <unk>
    assert "<unk>" in ds.word_idx and "cat" in ds.word_idx
    first = ds[0]
    assert len(first) == 2          # window-size tuples
    seq = Imikolov(data_file=path, data_type="SEQ", mode="test",
                   min_word_freq=3)
    src, tgt = seq[0]
    assert len(src) == len(tgt)     # shifted pair


def _imdb_tar(tmp_path):
    path = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0_9.txt": b"a great great movie",
        "aclImdb/train/neg/0_1.txt": b"a terrible movie",
        "aclImdb/test/pos/0_8.txt": b"great fun",
        "aclImdb/test/neg/0_2.txt": b"terrible bore",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, blob in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return str(path)


def test_imdb_labels_and_vocab(tmp_path):
    path = _imdb_tar(tmp_path)
    train = Imdb(data_file=path, mode="train", cutoff=10)
    assert len(train) == 2
    ids0, label0 = train[0]
    ids1, label1 = train[1]
    assert label0 == 0 and label1 == 1      # pos=0, neg=1 (reference)
    # 'great' appears twice in train -> ranked ahead of singletons
    assert train.word_idx["great"] < train.word_idx["terrible"]
    test = Imdb(data_file=path, mode="test", cutoff=10)
    assert len(test) == 2


def test_download_disabled_raises():
    with pytest.raises(RuntimeError, match="zero egress"):
        UCIHousing()
    with pytest.raises(RuntimeError, match="zero egress"):
        Imdb()


def test_imdb_external_word_idx(tmp_path):
    # the legacy dataset.imdb.train(word_dict) contract: samples encode
    # with the CALLER's vocabulary, not a rebuilt one
    path = _imdb_tar(tmp_path)
    custom = {"great": 0, "movie": 1}
    ds = Imdb(data_file=path, mode="train", word_idx=custom)
    assert ds.word_idx["<unk>"] == 2
    ids0, _ = ds[0]  # "a great great movie" -> unk, 0, 0, 1
    assert list(ids0) == [2, 0, 0, 1]

    import paddle_tpu as paddle
    reader = paddle.dataset.imdb.train(custom, data_file=path)
    ids, label = next(iter(reader()))
    assert list(ids) == [2, 0, 0, 1] and label == 0


def test_conll05st_tarball(tmp_path):
    import gzip
    from paddle_tpu.text import Conll05st
    # two sentences; sentence 1 has one predicate column
    words = ["The", "cat", "sat", "", "Dogs", "run", ""]
    props = ["-\t(A0*", "-\t*)", "sat\t(V*)", "", "-\t(A0*)", "run\t(V*)",
             ""]
    # build words.gz / props.gz inside the release layout
    wblob = gzip.compress(("\n".join(words) + "\n").encode())
    # props columns: verb lemma, then per-predicate span tags
    pblob = gzip.compress(("\n".join(props) + "\n").encode())
    tar = tmp_path / "conll05st-tests.tar"
    with tarfile.open(tar, "w") as tf:
        for name, blob in (
                ("conll05st-release/test.wsj/words/test.wsj.words.gz",
                 wblob),
                ("conll05st-release/test.wsj/props/test.wsj.props.gz",
                 pblob)):
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    wd = tmp_path / "words.dict"
    wd.write_text("\n".join(["The", "cat", "sat", "Dogs", "run"]) + "\n")
    vd = tmp_path / "verbs.dict"
    vd.write_text("sat\nrun\n")
    td = tmp_path / "targets.dict"
    td.write_text("B-A0\nB-V\n")
    ds = Conll05st(data_file=str(tar), word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(td),
                   emb_file="emb.txt")
    assert len(ds) == 2
    sample = ds[0]
    assert len(sample) == 9
    word_idx = sample[0]
    np.testing.assert_array_equal(word_idx, [0, 1, 2])  # The cat sat
    mark = sample[7]
    assert mark[2] == 1                                  # verb position
    label_ids = sample[8]
    wdict, vdict, ldict = ds.get_dict()
    assert vdict == {"sat": 0, "run": 1}
    assert ldict["O"] == len(ldict) - 1
    assert label_ids[0] == ldict["B-A0"]
    assert ds.get_embedding() == "emb.txt"


def test_movielens_zip(tmp_path):
    import zipfile
    from paddle_tpu.text import Movielens
    path = tmp_path / "ml-1m.zip"
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Heat (1995)::Action\n")
    users = "1::M::25::4::90210\n2::F::35::7::10001\n"
    ratings = ("1::1::5::978300760\n1::2::3::978302109\n"
               "2::1::4::978301968\n")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    train = Movielens(data_file=str(path), mode="train", test_ratio=0.0)
    assert len(train) == 3
    sample = train[0]
    # (uid, gender, age_idx, job, mov_id, categories, title_words, rating)
    assert len(sample) == 8
    uid, gender, age, job = (int(sample[0][0]), int(sample[1][0]),
                             int(sample[2][0]), int(sample[3][0]))
    assert uid == 1 and gender == 0 and job == 4
    rating = float(sample[-1][0])
    assert rating == 5.0 * 2 - 5.0        # reference rescale *2-5
    test = Movielens(data_file=str(path), mode="test", test_ratio=1.0)
    assert len(test) == 3


def _wmt14_tar(tmp_path):
    path = tmp_path / "wmt14.tgz"
    src_dict = "<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = "<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = "hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(path, "w:gz") as tf:
        for name, text in (("wmt14/src.dict", src_dict),
                           ("wmt14/trg.dict", trg_dict),
                           ("wmt14/train/train", train),
                           ("wmt14/test/test", train)):
            blob = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return str(path)


def test_wmt14_framing(tmp_path):
    from paddle_tpu.text import WMT14
    ds = WMT14(data_file=_wmt14_tar(tmp_path), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    sd, td = ds.get_dict()
    np.testing.assert_array_equal(
        src, [sd["<s>"], sd["hello"], sd["world"], sd["<e>"]])
    np.testing.assert_array_equal(
        trg, [td["<s>"], td["bonjour"], td["monde"]])
    np.testing.assert_array_equal(
        trg_next, [td["bonjour"], td["monde"], td["<e>"]])
    rsd, _ = ds.get_dict(reverse=True)
    assert rsd[sd["hello"]] == "hello"


def test_wmt16_dict_build_and_lang_swap(tmp_path):
    from paddle_tpu.text import WMT16
    path = tmp_path / "wmt16.tar"
    train = ("the cat\tdie katze\n"
             "the dog\tder hund\n")
    with tarfile.open(path, "w") as tf:
        for name, text in (("wmt16/train", train), ("wmt16/val", train),
                           ("wmt16/test", train)):
            blob = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    ds = WMT16(data_file=str(path), mode="train", src_dict_size=10,
               trg_dict_size=10, lang="en")
    src, trg, trg_next = ds[0]
    en = ds.get_dict("en")
    de = ds.get_dict("de")
    assert en["<s>"] == 0 and en["the"] == 3   # freq-sorted after markers
    np.testing.assert_array_equal(
        src, [en["<s>"], en["the"], en["cat"], en["<e>"]])
    np.testing.assert_array_equal(
        trg_next, [de["die"], de["katze"], de["<e>"]])
    # lang="de": source and target swap
    ds_de = WMT16(data_file=str(path), mode="train", src_dict_size=10,
                  trg_dict_size=10, lang="de")
    src_de, _, _ = ds_de[0]
    de2 = ds_de.get_dict("de")
    np.testing.assert_array_equal(
        src_de, [de2["<s>"], de2["die"], de2["katze"], de2["<e>"]])
