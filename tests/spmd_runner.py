"""Worker script: COMPILED SPMD programs across real processes.

Spawned by the launch CLI (2 processes x 4 local CPU devices = one global
8-device mesh through jax.distributed). Round-2 verdict item #1: every
compiled distributed program had only ever run single-controller; this
runner executes them across a genuine process boundary (the reference's
backbone shape — one process per host, process_group_nccl.cc:267; the
end-to-end pattern test/legacy_test/test_dist_base.py):

  [A] GSPMD dp x mp fused TrainStep — dp axis SPANS the two processes, so
      the gradient all-reduce crosses the boundary. 20 steps; rank 0
      records the loss curve + final (gathered) params for parity with a
      single-process run in the parent test.
  [B] generic hybrid pipeline step (build_hybrid_step) on a pp x dp mesh —
      the pp axis spans the processes, so ppermute activation hops cross
      the boundary. Records loss + grad-finiteness.
  [C] sharded distributed checkpoint: save the mp-sharded params from [A]
      (every process writes only its addressable shards), reload under a
      DIFFERENT mesh layout (reshard-on-load across the process boundary),
      assert exact roundtrip.
"""
import json
import os

if __name__ == "__main__":  # worker process: 4 local devices of the 8
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax
import jax.numpy as jnp

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import Replicate, Shard  # noqa: E402
from paddle_tpu.distributed.api import shard_parameter, shard_tensor  # noqa: E402


class MLP(paddle.nn.Layer):
    """Megatron-style 2-layer MLP: fc1 column-parallel, fc2 row-parallel."""

    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(16, 32)
        self.fc2 = paddle.nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def build_and_train(mesh, n_steps=20):
    """The [A] program. Deterministic given paddle.seed — the parent test
    re-runs it single-process for parity."""
    paddle.seed(0)
    model = MLP()
    rep = [Replicate()] * mesh.ndim
    mp_i = mesh.dim_names.index("mp")
    col = list(rep); col[mp_i] = Shard(1)      # fc1 W [in, out]: split out
    row = list(rep); row[mp_i] = Shard(0)      # fc2 W [in, out]: split in
    shard_parameter(model.fc1.weight, mesh, col)
    shard_parameter(model.fc1.bias, mesh,
                    [Shard(0) if i == mp_i else Replicate()
                     for i in range(mesh.ndim)])
    shard_parameter(model.fc2.weight, mesh, row)
    shard_parameter(model.fc2.bias, mesh, rep)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 16)).astype(np.float32)
    w_true = rng.standard_normal((16, 4)).astype(np.float32)
    y = x @ w_true

    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model,
        lambda xb, yb: paddle.nn.functional.mse_loss(model(xb), yb),
        opt)

    dp_pl = [Shard(0) if n == "dp" else Replicate() for n in mesh.dim_names]
    xt = shard_tensor(paddle.to_tensor(x), mesh, dp_pl)
    yt = shard_tensor(paddle.to_tensor(y), mesh, dp_pl)
    losses = [float(step(xt, yt).numpy()) for _ in range(n_steps)]
    return model, losses


def main():
    dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()
    assert world == 2, f"runner expects 2 processes, got {world}"
    assert len(jax.devices()) == 8, (
        f"expected an 8-device global mesh, got {len(jax.devices())}")
    result = {"n_global_devices": len(jax.devices())}

    # ---- [A] dp(2, across processes) x mp(4) fused TrainStep ----
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    model, losses = build_and_train(mesh)
    result["A_losses"] = losses
    # gather final params for the parity check (replicated-readable)
    final = {}
    for name, p in model.named_parameters():
        rep = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
        final[name] = np.asarray(rep.numpy()).tolist()
    result["A_params"] = final

    # ---- [B] pipeline across the process boundary: pp(2) x dp(4) ----
    from paddle_tpu.distributed.hybrid_parallel import build_hybrid_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh2 = dist.init_mesh({"pp": 2, "dp": 4})
    paddle.seed(3)
    dmodel = 8

    class Block(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(dmodel, dmodel)

        def forward(self, x):
            return x + paddle.tanh(self.fc(x))

    blocks = [Block() for _ in range(4)]
    gp, gstep = build_hybrid_step(
        blocks, lambda yy, ll: jnp.mean((yy - ll) ** 2), mesh2,
        n_micro=2, schedule="1f1b")
    # place stacked block params on the pp axis (global arrays)
    jm = mesh2.jax_mesh
    gp = {"blocks": jax.tree.map(
        lambda l: jax.make_array_from_callback(
            l.shape, NamedSharding(jm, P("pp")),
            lambda idx, l=l: np.ascontiguousarray(np.asarray(l)[idx])),
        gp["blocks"])}
    xb_np = np.random.default_rng(4).standard_normal(
        (8, 4, dmodel)).astype(np.float32)
    xb = jax.make_array_from_callback(
        xb_np.shape, NamedSharding(jm, P()), lambda idx: xb_np[idx])
    gl, ggrads = jax.jit(gstep)(gp, xb, jnp.zeros_like(xb))
    result["B_loss"] = float(gl)
    result["B_grads_finite"] = all(
        bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(ggrads))

    # ---- [C] sharded checkpoint save + reshard-on-load ----
    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict)

    ckpt_dir = os.environ["SPMD_CKPT_DIR"]
    state = {n: p for n, p in model.named_parameters()}
    save_state_dict(state, ckpt_dir)
    dist.barrier()
    # destination: a different layout — mp degree 2 on the FIRST axis,
    # dp 4 on the second; every tensor re-places across the boundary
    mesh3 = dist.init_mesh({"mp": 2, "dp": 4})
    paddle.seed(1)
    dest = MLP()
    mp_i = mesh3.dim_names.index("mp")
    dst_state = {n: p for n, p in dest.named_parameters()}
    shard_parameter(dest.fc1.weight, mesh3,
                    [Shard(1) if i == mp_i else Replicate()
                     for i in range(mesh3.ndim)])
    load_state_dict(dst_state, ckpt_dir)
    ok = True
    for n, p in dest.named_parameters():
        rep = shard_tensor(p, mesh3, [Replicate()] * mesh3.ndim)
        ok = ok and bool(np.allclose(np.asarray(rep.numpy()),
                                     np.asarray(result["A_params"][n])))
    result["C_roundtrip_ok"] = ok

    # ---- [D] cross-mesh reshard across the process boundary ----
    # live-tensor analog of [C]: an mp-sharded GLOBAL tensor moves onto a
    # sub-mesh owned entirely by process 0, then back onto a permuted
    # global mesh (reference: same_status / global<->sub-mesh reshard)
    from paddle_tpu.distributed.mesh import ProcessMesh

    devs = [d.id for d in jax.devices()]
    mesh_g = dist.init_mesh({"dp": 2, "mp": 4})
    sub = ProcessMesh(np.asarray(devs[:4]), ["mp"])     # process 0 only
    perm = ProcessMesh(np.asarray(devs[::-1]).reshape(4, 2), ["mp", "dp"])
    val = np.arange(32, dtype=np.float32).reshape(8, 4)
    tg = shard_tensor(paddle.to_tensor(val), mesh_g,
                      [Shard(0), Shard(1)])
    ts = dist.reshard(tg, sub, [Shard(0)])
    ok_d = True
    if rank == 0:   # only process 0 can read the sub-mesh tensor
        ok_d = bool(np.array_equal(np.asarray(ts.numpy()), val))
    tb = dist.reshard(ts, perm, [Shard(1), Replicate()])
    ok_d = ok_d and bool(np.array_equal(np.asarray(
        dist.reshard(tb, mesh_g, [Replicate(), Replicate()]).numpy()), val))
    result["D_cross_mesh_ok"] = ok_d

    dist.barrier()
    if rank == 0:
        with open(os.environ["SPMD_OUT"], "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    main()
