"""Sparse tensors + quantization.

Mirrors the reference's test/legacy_test sparse/quant unit tests at the
public API surface.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse
from paddle_tpu.quantization import (
    AbsmaxObserver, EMAObserver, QAT, QuantConfig, FakeQuanterWithAbsMax,
    fake_quantize)

# compile-heavy: slow tier (fast tier stays < 4 min, pytest.ini contract)
pytestmark = pytest.mark.slow


def test_sparse_coo_roundtrip():
    idx = np.array([[0, 1, 2], [1, 0, 2]])
    val = np.array([1.0, 2.0, 3.0], np.float32)
    s = sparse.sparse_coo_tensor(idx, val, shape=(3, 3))
    assert s.nnz() == 3
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[idx[0], idx[1]] = val
    np.testing.assert_array_equal(dense, expect)
    # back to sparse
    s2 = sparse.to_sparse_coo(paddle.to_tensor(expect))
    np.testing.assert_array_equal(s2.to_dense().numpy(), expect)


def test_sparse_csr():
    crows = np.array([0, 1, 3])
    cols = np.array([1, 0, 1])
    vals = np.array([5.0, 1.0, 2.0], np.float32)
    s = sparse.sparse_csr_tensor(crows, cols, vals, shape=(2, 2))
    np.testing.assert_array_equal(s.to_dense().numpy(),
                                  [[0, 5], [1, 2]])


def test_sparse_matmul_and_unary():
    rng = np.random.RandomState(0)
    dense_np = rng.randn(8, 8).astype(np.float32)
    dense_np[np.abs(dense_np) < 1.0] = 0.0  # sparsify
    s = sparse.to_sparse_coo(paddle.to_tensor(dense_np))
    d = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    out = sparse.matmul(s, d)
    np.testing.assert_allclose(out.numpy(), dense_np @ d.numpy(),
                               rtol=1e-4, atol=1e-5)
    r = sparse.relu(s)
    np.testing.assert_array_equal(r.to_dense().numpy(),
                                  np.maximum(dense_np, 0))


def test_fake_quantize_ste_grad():
    x = paddle.to_tensor(np.linspace(-1, 1, 16, dtype=np.float32),
                         stop_gradient=False)
    scale = paddle.to_tensor(1.0)
    y = fake_quantize(x, scale, bits=8)
    err = np.abs(y.numpy() - x.numpy()).max()
    assert err <= 1.0 / 127 + 1e-6  # quantization error bounded by one step
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 1.0)  # straight-through


def test_observers():
    ob = AbsmaxObserver()
    ob(paddle.to_tensor(np.array([1.0, -3.0], np.float32)))
    ob(paddle.to_tensor(np.array([2.0], np.float32)))
    assert float(ob.scales().numpy()) == 3.0
    ema = EMAObserver(moving_rate=0.5)
    ema(paddle.to_tensor(np.array([4.0], np.float32)))
    ema(paddle.to_tensor(np.array([2.0], np.float32)))
    assert 2.0 < float(ema.scales().numpy()) < 4.0


def test_qat_quantize_and_train():
    import paddle_tpu.nn as nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                      weight=FakeQuanterWithAbsMax)
    qat = QAT(cfg)
    qmodel = qat.quantize(model)   # deep-copies: original stays fp
    from paddle_tpu.quantization import QuantedLayer
    assert not any(isinstance(l, QuantedLayer) for l in model.sublayers())
    assert any(isinstance(l, QuantedLayer) for l in qmodel.sublayers())
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = qmodel(x)
    assert y.shape == [4, 4]
    loss = (y * y).mean()
    loss.backward()
    grads = [p.grad for p in qmodel.parameters() if p.grad is not None]
    assert grads  # STE lets grads reach the fp weights


def test_sparse_nn_layers():
    """sparse.nn surface: Linear / activations / Softmax / BatchNorm
    (reference: python/paddle/sparse/nn/layer/)."""
    import paddle_tpu.sparse as sparse
    d = np.array([[0, 2, 0, 1], [3, 0, 0, 0], [0, 0, 0, 0]], np.float32)
    x = sparse.to_sparse_coo(paddle.to_tensor(d))

    lin = sparse.nn.Linear(4, 5)
    y = lin(x)
    ref = d @ np.asarray(lin.weight.numpy()) + np.asarray(lin.bias.numpy())
    np.testing.assert_allclose(np.asarray(y.numpy()), ref, rtol=1e-5)

    # d - 1.5 has no zeros: every entry is stored, activations apply to all
    shifted = d - 1.5
    neg = sparse.to_sparse_coo(paddle.to_tensor(shifted))
    r = sparse.nn.ReLU()(neg).to_dense().numpy()
    np.testing.assert_allclose(np.asarray(r), np.maximum(shifted, 0))
    lr = sparse.nn.LeakyReLU(0.1)(neg).to_dense().numpy()
    np.testing.assert_allclose(
        np.asarray(lr), np.where(shifted >= 0, shifted, 0.1 * shifted),
        rtol=1e-6)

    sm = sparse.nn.Softmax()(x).to_dense().numpy()
    e = np.exp(np.array([2.0, 1.0]) - 2.0)
    e = e / e.sum()
    np.testing.assert_allclose([sm[0, 1], sm[0, 3]], e, rtol=1e-5)
    np.testing.assert_allclose(sm[1, 0], 1.0, rtol=1e-6)

    # BatchNorm over a dense feature axis (point-cloud layout [N, C])
    pts = np.array([[1.0, 2.0, 0.5], [3.0, -1.0, 2.5]], np.float32)
    dense = np.zeros((4, 3), np.float32)
    dense[[0, 2]] = pts
    xc = sparse.to_sparse_coo(paddle.to_tensor(dense), sparse_dim=1)
    bn = sparse.nn.BatchNorm(3)
    out = bn(xc)
    vals = np.asarray(out._bcoo.data)
    np.testing.assert_allclose(vals.mean(axis=0), 0.0, atol=1e-5)
    with pytest.raises(ValueError, match="feature dim"):
        sparse.nn.BatchNorm(3)(x)


def test_int8_inference_path():
    """Weight-only + dynamic int8 Linear (reference capability: int8
    inference quantization passes)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.quantization import (
        quantize_for_inference, Int8Linear, quantize_to_int8)
    paddle.seed(0)

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 8)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    m = M()
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((4, 16)).astype(np.float32))
    ref = m(x).numpy()
    for mode in ("weight_only", "dynamic"):
        qm = quantize_for_inference(m, mode=mode)
        assert isinstance(qm.fc1, Int8Linear)
        assert str(qm.fc1.w_int8.dtype) == "int8"
        out = qm(x).numpy()
        rel = np.abs(np.asarray(out) - np.asarray(ref)).max() / \
            (np.abs(np.asarray(ref)).max() + 1e-9)
        assert rel < 0.05, (mode, rel)
    # original model untouched by the copy-quantize
    np.testing.assert_allclose(np.asarray(m(x).numpy()), np.asarray(ref))
    # quantizer roundtrip error is bounded by one step
    w = np.random.default_rng(1).standard_normal((8, 8)).astype(np.float32)
    q, s = quantize_to_int8(paddle.to_tensor(w), axis=1)
    np.testing.assert_allclose(np.asarray(q, np.float32) * np.asarray(s), w,
                               atol=float(np.asarray(s).max()))
