"""Fused multi-tensor optimizer parity (optimizer/fused.py).

The fused engine — dtype-bucketed flat updates with fused global-norm
clipping — must be numerically indistinguishable from the per-parameter
loop for every supported optimizer, across L1/L2 decay, the AdamW hooks,
mixed f32/bf16 param sets, and params excluded by stop_gradient / missing
grads. The per-param loop (FLAGS_fused_optimizer=False) is the reference.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import GLOBAL_FLAGS

F32_TOL = 1e-6
BF16_TOL = 2e-2  # one bf16 ulp near 1.0 is ~8e-3


@pytest.fixture
def fused_flag():
    yield
    GLOBAL_FLAGS.set("fused_optimizer", True)


MIXED_SPECS = ([((4, 8), "float32"), ((16,), "float32"), ((), "float32"),
                ((3, 3, 2), "float32"), ((8, 4), "bfloat16"),
                ((5,), "bfloat16")] * 3)


def _make_params(specs, seed=0):
    rng = np.random.default_rng(seed)
    params = []
    for i, (shape, dtype) in enumerate(specs):
        t = paddle.to_tensor(
            rng.standard_normal(shape).astype(np.float32), dtype=dtype)
        t.stop_gradient = False
        t.name = f"p{i}"
        t.grad = paddle.to_tensor(
            rng.standard_normal(shape).astype(np.float32), dtype=dtype)
        params.append(t)
    return params


def _run(factory, fused, specs=MIXED_SPECS, steps=3, seed=0):
    GLOBAL_FLAGS.set("fused_optimizer", fused)
    params = _make_params(specs, seed)
    opt = factory(params)
    for _ in range(steps):
        opt.step()
    vals = [np.asarray(p.numpy(), np.float64) for p in params]
    state = opt.state_dict()
    return params, vals, state, opt


def _assert_match(specs, a_vals, b_vals):
    for (shape, dtype), a, b in zip(specs, a_vals, b_vals):
        tol = F32_TOL if dtype == "float32" else BF16_TOL
        np.testing.assert_allclose(a, b, atol=tol, rtol=tol,
                                   err_msg=f"{shape} {dtype}")


CASES = {
    "sgd_l2": lambda ps: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=ps, weight_decay=0.01),
    "sgd_l1": lambda ps: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=ps,
        weight_decay=paddle.regularizer.L1Decay(0.01)),
    "momentum_nesterov_clip": lambda ps: paddle.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, use_nesterov=True, parameters=ps,
        weight_decay=0.01, grad_clip=paddle.nn.ClipGradByGlobalNorm(0.5)),
    "adam_clip": lambda ps: paddle.optimizer.Adam(
        learning_rate=0.01, parameters=ps, weight_decay=0.02,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0)),
    "adamw_hooks": lambda ps: paddle.optimizer.AdamW(
        learning_rate=0.01, parameters=ps, weight_decay=0.05,
        apply_decay_param_fun=lambda n: not n.endswith("1"),
        lr_ratio=lambda p: 0.5 if p.name.endswith("2") else 1.0,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0, auto_skip_clip=True)),
    "adamw_byvalue": lambda ps: paddle.optimizer.AdamW(
        learning_rate=0.01, parameters=ps,
        grad_clip=paddle.nn.ClipGradByValue(0.3)),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_fused_matches_per_param(case, fused_flag):
    factory = CASES[case]
    _, fused_vals, fused_state, fused_opt = _run(factory, True)
    _, ref_vals, ref_state, _ = _run(factory, False)
    _assert_match(MIXED_SPECS, fused_vals, ref_vals)
    eng = fused_opt._fused_engine
    assert eng is not None and eng.active
    assert len(eng.buckets) == 2  # one f32, one bf16
    # optimizer state matches through the state_dict view too
    assert set(fused_state) == set(ref_state)
    for k in fused_state:
        a, b = fused_state[k], ref_state[k]
        if hasattr(a, "numpy"):
            np.testing.assert_allclose(
                np.asarray(a.numpy(), np.float64),
                np.asarray(b.numpy(), np.float64),
                atol=BF16_TOL if "bfloat16" in str(a.dtype) else F32_TOL,
                rtol=BF16_TOL, err_msg=k)


def test_build_excludes_stop_gradient_and_missing_grads(fused_flag):
    GLOBAL_FLAGS.set("fused_optimizer", True)
    params = _make_params(MIXED_SPECS[:8], seed=1)
    params[1].stop_gradient = True
    params[3].grad = None
    frozen = [np.asarray(params[i].numpy()).copy() for i in (1, 3)]
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=params)
    opt.step()
    eng = opt._fused_engine
    bucketed = {id(p) for b in eng.buckets for p in b.params}
    assert id(params[1]) not in bucketed
    assert id(params[3]) not in bucketed
    for i, v in zip((1, 3), frozen):
        assert np.array_equal(v, np.asarray(params[i].numpy()))


def test_mid_run_grad_drop_masks_without_rebuild(fused_flag):
    """A param losing its grad mid-run (MoE expert off-route) takes the
    masked-subset path: untouched value AND state, no bucket rebuild."""
    GLOBAL_FLAGS.set("fused_optimizer", True)
    params = _make_params([((4, 4), "float32")] * 6, seed=2)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=params)
    opt.step()
    eng = opt._fused_engine
    buckets0 = list(eng.buckets)
    params[2].grad = None
    before = np.asarray(params[2].numpy()).copy()
    m_before = np.asarray(opt._param_state(params[2])["moment1"])
    m3_before = np.asarray(opt._param_state(params[3])["moment1"])
    opt.step()
    assert np.array_equal(before, np.asarray(params[2].numpy()))
    m_after = np.asarray(opt._param_state(params[2])["moment1"])
    np.testing.assert_array_equal(m_before, m_after)
    # _param_state views are FRESH, not cached copies: a participating
    # param's moment must have moved across the masked step
    m3_after = np.asarray(opt._param_state(params[3])["moment1"])
    assert not np.array_equal(m3_before, m3_after)
    assert eng.buckets == buckets0  # masked, not rebuilt


def test_state_dict_roundtrip_across_paths(fused_flag):
    """fused -> state_dict -> per-param continuation equals a pure
    per-param run; the flat buffers and per-param views are one state."""
    factory = CASES["adam_clip"]
    # reference: 3 per-param steps
    _, ref_vals, _, _ = _run(factory, False, steps=3)
    # fused 2 steps, hand off through state_dict to a per-param optimizer
    params, _, _, opt = _run(factory, True, steps=2)
    sd = opt.state_dict()
    GLOBAL_FLAGS.set("fused_optimizer", False)
    opt2 = factory(params)
    opt2.set_state_dict(sd)
    opt2.step()
    _assert_match(MIXED_SPECS,
                  [np.asarray(p.numpy(), np.float64) for p in params],
                  ref_vals)


def test_trainstep_consumes_fused_buckets(fused_flag):
    """jit.TrainStep primes the engine: compiled losses match the
    per-param compiled path and the flat state advances across steps."""
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((16, 8)).astype(np.float32))

    def build():
        paddle.seed(7)
        m = paddle.nn.Linear(8, 4)
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-2, parameters=m.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        step = paddle.jit.TrainStep(m, lambda x: (m(x) ** 2).mean(), opt)
        return opt, step

    GLOBAL_FLAGS.set("fused_optimizer", True)
    opt_f, step_f = build()
    fused_losses = [float(step_f(x).numpy()) for _ in range(5)]
    eng = opt_f._fused_engine
    assert eng is not None and eng.active
    GLOBAL_FLAGS.set("fused_optimizer", False)
    _, step_p = build()
    ref_losses = [float(step_p(x).numpy()) for _ in range(5)]
    np.testing.assert_allclose(fused_losses, ref_losses, atol=1e-5)
    assert fused_losses[-1] < fused_losses[0]
    # flat state is real state: it round-trips through state_dict
    sd = opt_f.state_dict()
    assert any(".moment1" in k for k in sd)


def test_fused_adamw_pallas_kernel_parity():
    """The Pallas bucket kernel (interpret mode) matches the jnp body,
    padding included (n not a multiple of the 128-lane tile)."""
    import jax.numpy as jnp
    from paddle_tpu.kernels.fused_adamw import fused_adamw, _reference

    rng = np.random.default_rng(0)
    n = 1000
    for dt, tol in ((jnp.float32, 1e-6), (jnp.bfloat16, 2e-2)):
        p = jnp.asarray(rng.standard_normal(n), dt)
        g = jnp.asarray(rng.standard_normal(n), dt)
        m = jnp.asarray(rng.standard_normal(n), jnp.float32)
        v = jnp.asarray(np.abs(rng.standard_normal(n)), jnp.float32)
        for decoupled in (True, False):
            out = fused_adamw(p, g, m, v, 0.01, 3, weight_decay=0.05,
                              decoupled=decoupled, block_rows=16,
                              interpret=True)
            ref = _reference(p, g, m, v, 0.01, 1 - 0.9 ** 3, 1 - 0.999 ** 3,
                             beta1=0.9, beta2=0.999, eps=1e-8, wd=0.05,
                             decoupled=decoupled)
            for a, b in zip(out, ref):
                np.testing.assert_allclose(
                    np.asarray(a, np.float64), np.asarray(b, np.float64),
                    atol=tol, rtol=tol)


def test_engine_uses_pallas_kernel_when_forced(fused_flag, monkeypatch):
    """PADDLE_TPU_FORCE_PALLAS=1 routes the AdamW bucket update through the
    Pallas kernel (interpreter on CPU) with unchanged numerics."""
    monkeypatch.setenv("PADDLE_TPU_FORCE_PALLAS", "1")

    def factory(ps):
        return paddle.optimizer.AdamW(learning_rate=0.01, parameters=ps,
                                      weight_decay=0.01)

    specs = [((8, 16), "float32")] * 4
    _, forced_vals, _, _ = _run(factory, True, specs=specs, steps=2)
    monkeypatch.delenv("PADDLE_TPU_FORCE_PALLAS")
    _, ref_vals, _, _ = _run(factory, False, specs=specs, steps=2)
    _assert_match(specs, forced_vals, ref_vals)


def test_opt_out_flag_restores_per_param_loop(fused_flag):
    GLOBAL_FLAGS.set("fused_optimizer", False)
    params = _make_params(MIXED_SPECS[:4], seed=3)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=params)
    opt.step()
    assert opt._fused_engine is None
