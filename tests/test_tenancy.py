"""paddle_tpu.tenancy gates (ISSUE 17): batched per-request LoRA
through the ONE ragged executable (slot 0 = zeros = the base model,
bitwise), refcounted hot-add/evict with zero recompiles, ArtifactStore
persistence, the weighted-fair tenant economy (stride admission, token
quotas, cost ledgers, per-tenant burn alerts), seeded noisy-neighbor
reproducibility, and the tune->serve bridge over the masked fused
optimizer."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.loadgen import (Driver, VirtualClock, WorkloadSpec,
                                build_report, report_json,
                                trace_fingerprint)
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import LLMEngine, RequestRejected, RequestTracer
from paddle_tpu.tenancy import (AdapterInUse, AdapterRegistry,
                                AdapterSlotsFull, AdapterStoreMismatch,
                                AdapterTuner, UnknownAdapter,
                                make_random_adapter, tenant_burn_rules)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


def _prompt(n, seed=0, v=128):
    return np.random.default_rng(seed).integers(0, v, (n,)).tolist()


ENG = dict(max_len=64, page_size=4, seed=0)


# ---------------------------------------------------------------------------
# the slab: slot 0 identity, mixed batches, hot-swap without recompile


@pytest.mark.parametrize("quant", [None, "weight_only_int8"])
def test_mixed_batch_base_rows_bitwise_identical(tiny_model, quant):
    """A mixed batch — one LoRA-adapted row, one base row — decodes
    through the one ragged executable with the base row BIT-identical
    to a no-adapter engine (slot 0 is all-zeros: base(x) + 0.0), and
    the adapted row identical to a solo engine wearing the same
    adapter. Over the fp AND the int8-quantized base."""
    kw = dict(ENG, quantized_mode=quant) if quant else dict(ENG)
    prompt = _prompt(6, seed=5)
    ad = make_random_adapter(tiny_model.config, rank=4, seed=3,
                             scale=0.5)

    eng0 = LLMEngine(tiny_model, **kw)
    r0 = eng0.add_request(prompt, max_new_tokens=6)
    base = eng0.run(max_steps=200)[r0].token_ids

    solo = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4, **kw)
    solo.add_adapter("t1", ad)
    rs = solo.add_request(prompt, max_new_tokens=6, adapter_id="t1")
    adapted = solo.run(max_steps=200)[rs].token_ids

    mixed = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4, **kw)
    mixed.add_adapter("t1", ad)
    ra = mixed.add_request(prompt, max_new_tokens=6, adapter_id="t1")
    rb = mixed.add_request(prompt, max_new_tokens=6)
    outs = mixed.run(max_steps=200)
    assert outs[rb].token_ids == base, \
        "base row in a mixed batch diverged from the no-adapter engine"
    assert outs[ra].token_ids == adapted, \
        "adapted row in a mixed batch diverged from the solo engine"
    assert outs[ra].token_ids != base, \
        "the adapter delta must be visible (scale 0.5 factors)"
    assert mixed.decode_cache_size() == 1


def test_no_adapter_engine_hlo_is_byte_identical(tiny_model):
    """adapter_slots=0 passes None for both trailing jit operands —
    empty pytrees, ZERO added HLO operands: the executable an engine
    without the feature compiles is byte-identical to the pre-tenancy
    one. Gated structurally: same compiled text with and without the
    tenancy import having ever run."""
    e1 = LLMEngine(tiny_model, **ENG)
    e2 = LLMEngine(tiny_model, **ENG)
    r1 = e1.add_request(_prompt(5), max_new_tokens=3)
    r2 = e2.add_request(_prompt(5), max_new_tokens=3)
    assert e1.run(max_steps=100)[r1].token_ids == \
        e2.run(max_steps=100)[r2].token_ids
    assert e1.decode_cache_size() == e2.decode_cache_size() == 1
    snap = e1.metrics_snapshot()
    assert snap["tenants"] is None
    assert snap["adapter_slots"] is None


def test_hot_add_evict_zero_recompiles(tiny_model):
    """Publishing, republishing, and evicting adapters rewrites slab
    rows in place — decode_cache_size() stays 1 through the whole
    churn, and the registry counters fold into metrics exactly once."""
    eng = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4, **ENG)
    prompt = _prompt(5, seed=1)
    eng.add_request(prompt, max_new_tokens=4)
    eng.run(max_steps=100)
    assert eng.decode_cache_size() == 1

    eng.add_adapter("t1", make_random_adapter(
        tiny_model.config, rank=4, seed=1, scale=0.5))
    eng.add_request(prompt, max_new_tokens=4, adapter_id="t1")
    eng.run(max_steps=100)
    eng.add_adapter("t2", make_random_adapter(
        tiny_model.config, rank=4, seed=2, scale=0.5))
    eng.evict_adapter("t1")
    eng.add_adapter("t3", make_random_adapter(
        tiny_model.config, rank=4, seed=3, scale=0.5))
    eng.add_request(prompt, max_new_tokens=4, adapter_id="t3")
    eng.run(max_steps=100)
    assert eng.decode_cache_size() == 1, \
        "adapter churn must never add a step executable"
    snap = eng.metrics_snapshot()
    assert snap["adapter_hot_adds"] == 3
    assert snap["adapter_evictions"] == 1
    assert snap["adapter_slots_used"] == 2
    assert snap["adapter_slots"] == 2
    # repeated snapshots must not double-count the folded deltas
    assert eng.metrics_snapshot()["adapter_hot_adds"] == 3


def test_evict_while_referenced_refused_then_succeeds(tiny_model):
    """Evicting an adapter worn by an in-flight request raises a
    structured AdapterInUse (never a silent slot-0 fallback); after the
    request drains, the same evict succeeds."""
    eng = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4, **ENG)
    eng.add_adapter("t1", make_random_adapter(
        tiny_model.config, rank=4, seed=1))
    eng.add_request(_prompt(5), max_new_tokens=8, adapter_id="t1")
    eng.step()                      # in flight, wearing t1
    with pytest.raises(AdapterInUse) as ei:
        eng.evict_adapter("t1")
    assert ei.value.adapter_id == "t1" and ei.value.refcount == 1
    assert eng.metrics_snapshot()["adapter_evict_refusals"] == 1
    eng.run(max_steps=100)          # drain
    eng.evict_adapter("t1")
    assert eng.adapters.slots_used == 0
    # a finished request released its reference exactly once
    assert eng.adapters.refcount("t1") == 0


def test_unknown_adapter_is_structured_rejection(tiny_model):
    """A request naming an adapter the registry does not hold is
    rejected with a structured output — serving it the base model
    silently would be a correctness bug."""
    eng = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4, **ENG)
    with pytest.raises(RequestRejected):
        eng.add_request(_prompt(4), max_new_tokens=3,
                        adapter_id="nope", request_id="r-bad")
    out = eng.outputs()["r-bad"]
    assert out.status == "aborted"
    assert out.finish_reason == "rejected_unknown_adapter"
    # an engine with NO registry rejects the same way
    eng0 = LLMEngine(tiny_model, **ENG)
    with pytest.raises(RequestRejected):
        eng0.add_request(_prompt(4), max_new_tokens=3, adapter_id="x")


def test_registry_lru_eviction_and_slots_full(tiny_model):
    """Capacity pressure evicts the least-recently-used UNREFERENCED
    adapter; when every occupant is referenced the registry refuses
    with AdapterSlotsFull instead of picking a victim."""
    cfg = tiny_model.config
    reg = AdapterRegistry(cfg, n_slots=2, rank=4)
    reg.add("a", make_random_adapter(cfg, rank=4, seed=1))
    reg.add("b", make_random_adapter(cfg, rank=4, seed=2))
    slot_a = reg.slot_of("a")
    reg.add("c", make_random_adapter(cfg, rank=4, seed=3))  # evicts a
    assert reg.slot_of("c") == slot_a
    with pytest.raises(UnknownAdapter):
        reg.slot_of("a")
    assert reg.evictions == 1
    reg.acquire("b")
    reg.acquire("c")
    with pytest.raises(AdapterSlotsFull):
        reg.add("d", make_random_adapter(cfg, rank=4, seed=4))
    reg.release("b")
    reg.add("d", make_random_adapter(cfg, rank=4, seed=4))   # b is LRU
    with pytest.raises(UnknownAdapter):
        reg.slot_of("b")
    # slot 0 is the reserved base identity: never publishable
    with pytest.raises(ValueError):
        reg.add(0, make_random_adapter(cfg, rank=4))
    # wrong-rank factors are refused at the door
    with pytest.raises(ValueError):
        reg.add("r8", make_random_adapter(cfg, rank=8))


def test_adapter_store_roundtrip_and_geometry_gate(tiny_model, tmp_path):
    """Published adapters survive process death: a fresh engine on the
    same store warm-reloads them (adapter_restores counted) and serves
    token-identical outputs; a store whose geometry disagrees with the
    engine raises AdapterStoreMismatch instead of loading wrong-shape
    deltas."""
    root = str(tmp_path / "astore")
    prompt = _prompt(6, seed=9)
    e1 = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4,
                   adapter_store=root, **ENG)
    e1.add_adapter("t1", make_random_adapter(
        tiny_model.config, rank=4, seed=3, scale=0.5))  # autosaves
    r1 = e1.add_request(prompt, max_new_tokens=6, adapter_id="t1")
    toks = e1.run(max_steps=200)[r1].token_ids
    assert e1.metrics_snapshot()["adapter_store_saves"] >= 1

    e2 = LLMEngine(tiny_model, adapter_slots=2, adapter_rank=4,
                   adapter_store=root, **ENG)
    assert e2.metrics_snapshot()["adapter_restores"] == 1
    assert e2.adapters.adapter_ids() == ["t1"]
    r2 = e2.add_request(prompt, max_new_tokens=6, adapter_id="t1")
    assert e2.run(max_steps=200)[r2].token_ids == toks

    with pytest.raises(AdapterStoreMismatch):
        LLMEngine(tiny_model, adapter_slots=2, adapter_rank=8,
                  adapter_store=root, **ENG)
    # save_adapters dedups on the dirty bit
    assert e2.save_adapters() is False
    e2.add_adapter("t2", make_random_adapter(
        tiny_model.config, rank=4, seed=4))
    assert e2.adapters.dirty is False          # autosave already ran


# ---------------------------------------------------------------------------
# the economy: FIFO degradation, quotas, cost ledgers, alerts


def test_no_tenant_requests_keep_fifo_token_identity(tiny_model):
    """Declaring tenants but sending tenantless traffic degrades to
    exactly the classic engine: every request lands in the default
    bucket, stride order == FIFO order, outputs token-identical."""
    prompts = [_prompt(n, seed=n) for n in (4, 6, 8, 5)]
    plain = LLMEngine(tiny_model, **ENG)
    rids_p = [plain.add_request(p, max_new_tokens=4) for p in prompts]
    outs_p = plain.run(max_steps=200)

    tenanted = LLMEngine(tiny_model, tenants=[
        {"tenant_id": "a", "weight": 2.0},
        {"tenant_id": "b", "quota_tokens_per_s": 50.0}], **ENG)
    rids_t = [tenanted.add_request(p, max_new_tokens=4) for p in prompts]
    outs_t = tenanted.run(max_steps=200)
    for rp, rt in zip(rids_p, rids_t):
        assert outs_p[rp].token_ids == outs_t[rt].token_ids
    assert tenanted.metrics_snapshot()["tenants"] is not None
    assert plain.metrics_snapshot()["tenants"] is None


def test_quota_shed_is_structured_and_counted(tiny_model):
    """A metered tenant's overflow sheds with finish_reason
    "quota_exceeded" (structured, flight-recorded) while an unmetered
    tenant's traffic all finishes; counters and the ledger agree."""
    clock = VirtualClock()
    eng = LLMEngine(tiny_model, now_fn=clock.now, max_num_seqs=2,
                    tenants=[
                        {"tenant_id": "a", "weight": 3.0,
                         "quota_tokens_per_s": 1000.0},
                        {"tenant_id": "b", "quota_tokens_per_s": 8.0}],
                    **ENG)
    rids_a = [eng.add_request(_prompt(4, seed=i), max_new_tokens=4,
                              tenant_id="a") for i in range(3)]
    rids_b = [eng.add_request(_prompt(4, seed=10 + i), max_new_tokens=4,
                              tenant_id="b") for i in range(6)]
    for _ in range(400):
        if not eng.has_unfinished():
            break
        eng.step()
        clock.advance(0.05)
    outs = eng.outputs()
    assert all(outs[r].status == "finished" for r in rids_a), \
        "the unmetered tenant must be untouched by b's quota"
    shed = [r for r in rids_b if outs[r].status == "shed"]
    fin = [r for r in rids_b if outs[r].status == "finished"]
    assert shed and fin, "quota must shed the overflow, not everything"
    for r in shed:
        assert outs[r].finish_reason == "quota_exceeded"
    snap = eng.metrics_snapshot()
    assert snap["quota_shed_requests"] == len(shed)
    assert snap["tenants"]["b"]["quota_sheds"] == len(shed)
    assert snap["tenants"]["a"]["quota_sheds"] == 0
    # the sheds hit the flight recorder with tenant attribution
    shed_events = [f for _, k, f in eng.flight.events()
                   if k == "shed" and f and f.get("tenant") == "b"]
    assert len(shed_events) == len(shed)


def test_cost_attribution_ledgers(tiny_model):
    """Every resource a tenant consumes lands in its ledger: generated
    tokens (exact), time-weighted KV byte-seconds, and adapter-slot
    residency seconds — all > 0 only for the tenants that used them."""
    clock = VirtualClock()
    eng = LLMEngine(tiny_model, now_fn=clock.now, adapter_slots=2,
                    adapter_rank=4,
                    tenants=[{"tenant_id": "a"}, {"tenant_id": "b"}],
                    **ENG)
    eng.add_adapter("t1", make_random_adapter(
        tiny_model.config, rank=4, seed=1))
    eng.add_request(_prompt(4), max_new_tokens=6, tenant_id="a",
                    adapter_id="t1")
    eng.add_request(_prompt(4, seed=2), max_new_tokens=6, tenant_id="b")
    for _ in range(200):
        if not eng.has_unfinished():
            break
        eng.step()
        clock.advance(0.05)
    led = eng.metrics_snapshot()["tenants"]
    assert led["a"]["tokens"] == 6 and led["b"]["tokens"] == 6
    assert led["a"]["kv_byte_seconds"] > 0
    assert led["b"]["kv_byte_seconds"] > 0
    assert led["a"]["adapter_slot_seconds"] > 0, \
        "slab residency is billable"
    assert led["b"]["adapter_slot_seconds"] == 0.0
    assert led["a"]["ttft_p99_s"] is not None
    assert led["a"]["finished"] == led["b"]["finished"] == 1


def test_tenant_burn_alert_fires_by_name(tiny_model):
    """A tenant whose TTFT p99 burns its budget pages by NAME: the
    policy's slo_sample feeds tenant_burn_rules through an
    AlertManager, and only the burning tenant's rule fires."""
    from paddle_tpu.telemetry import AlertManager
    from paddle_tpu.tenancy import TenantPolicy
    pol = TenantPolicy([{"tenant_id": "good"}, {"tenant_id": "slow"}])
    am = AlertManager(tenant_burn_rules(["good", "slow"],
                                        ttft_p99_s=0.1,
                                        fast_window_s=0.2,
                                        slow_window_s=0.4))
    for i in range(10):
        pol.record_ttft("good", 0.01)
        pol.record_ttft("slow", 0.5)
        am.observe(0.1 * i, pol.slo_sample())
    fired = {e["slo"] for e in am.timeline if e["event"] == "firing"}
    assert fired == {"tenant:slow:ttft_p99"}, am.timeline


# ---------------------------------------------------------------------------
# loadgen: tenant mixes, classic fingerprints, noisy neighbor


def test_workload_tenant_validation_and_fingerprints():
    """Tenant-mix validation raises on malformed specs; the CLASSIC
    (no-tenant) trace fingerprints are pinned byte-for-byte (the
    tenant draw must not shift the classic rng stream), and a tenant
    spec fingerprints differently but self-reproducibly."""
    for bad in (
            [{"tenant_id": "a", "color": "red"}],       # unknown key
            [{"tenant_id": "a"}, {"tenant_id": "a"}],   # duplicate
            [{"tenant_id": ""}],                        # empty id
            [{"tenant_id": "a", "weight": 0.0}],        # weight <= 0
            [{"tenant_id": "a", "quota_tokens_per_s": -1}],
            [{"tenant_id": "a", "abusive": True},
             {"tenant_id": "b", "abusive": True}]):     # two abusers
        with pytest.raises(ValueError):
            WorkloadSpec(num_requests=4, tenants=bad)
    with pytest.raises(ValueError):
        WorkloadSpec(num_requests=4, abusive_multiplier=0.5,
                     tenants=[{"tenant_id": "a"}])

    # pinned classic fingerprints: tenancy must never move them
    def _fp(spec):
        return trace_fingerprint(spec.compile())

    assert _fp(WorkloadSpec(seed=7, num_requests=12)) == \
        "8212e986421fef8dc23568e0822b3b551e6bb0119331c71128d3d521f2918b66"
    assert _fp(WorkloadSpec(
        seed=3, num_requests=8, prompt_len=(4, 24), output_len=(4, 12),
        arrival="poisson", arrival_rate=8.0, temperature=0.7,
        top_k=(0, 8), top_p=(0.8, 1.0), shared_prefix_fraction=0.5,
        shared_prefix_len=3, num_shared_prefixes=2)) == \
        "6cdaa49a86986adcfbf89f634b67956c6f2d2dd379d3fe198bd2a5777ae4e1be"

    tspec = WorkloadSpec(seed=7, num_requests=12, tenants=[
        {"tenant_id": "a", "weight": 2.0}, {"tenant_id": "b"}])
    assert _fp(tspec) != _fp(WorkloadSpec(seed=7, num_requests=12))
    assert _fp(tspec) == _fp(tspec)
    tids = {r.tenant_id for r in tspec.compile()}
    assert tids <= {"a", "b"} and len(tids) == 2
    # tenant_specs() strips the loadgen-only "abusive" marker
    ab = WorkloadSpec(num_requests=4, tenants=[
        {"tenant_id": "n", "abusive": True, "weight": 1.0}])
    assert ab.tenant_specs() == [{"tenant_id": "n", "weight": 1.0}]


def test_abusive_tenant_floods_selection_share_only():
    """The abusive marker multiplies the tenant's SELECTION share (the
    flood) while its declared weight/quota stay honest — the scheduler
    sees the real entitlement, the trace sees the flood."""
    spec = WorkloadSpec(seed=0, num_requests=400, tenants=[
        {"tenant_id": "a", "weight": 2.0},
        {"tenant_id": "b", "weight": 1.0, "abusive": True}],
        abusive_multiplier=8.0)
    from collections import Counter
    counts = Counter(r.tenant_id for r in spec.compile())
    assert counts["b"] > 3 * counts["a"], counts


def test_noisy_neighbor_isolation_is_byte_reproducible(tiny_model):
    """The seeded noisy-neighbor scenario: the metered abuser's flood
    must not move the good tenant's p99 TTFT (isolation), the overflow
    sheds, the full report reproduces byte for byte per seed, and a
    classic (tenantless) run's report carries no tenants section."""
    spec = WorkloadSpec(
        num_requests=24, seed=11, arrival="poisson", arrival_rate=40.0,
        prompt_len=(4, 10), output_len=(3, 6), vocab_size=128,
        tenants=({"tenant_id": "good", "weight": 2.0},
                 {"tenant_id": "noisy", "weight": 1.0,
                  "quota_tokens_per_s": 60.0, "abusive": True}))

    def run():
        clock = VirtualClock()
        eng = LLMEngine(tiny_model, max_num_seqs=4, now_fn=clock.now,
                        tenants=spec.tenant_specs(), **ENG)
        res = Driver(eng, clock, step_time_s=0.02).run(spec.compile())
        return res, report_json(build_report(res, spec=spec,
                                             trace=spec.compile()))

    res1, rep1 = run()
    _, rep2 = run()
    assert rep1 == rep2, "the tenant report must be byte-reproducible"

    import json
    rep = json.loads(rep1)
    per = rep["tenants"]["per_tenant"]
    assert per["noisy"]["shed"] >= 1
    assert per["good"]["shed"] == 0
    good_p99 = per["good"]["ttft_s"]["p99"]
    noisy_p99 = per["noisy"]["ttft_s"]["p99"]
    assert good_p99 < 0.5 * noisy_p99, \
        f"isolation broke: good p99 {good_p99} vs noisy {noisy_p99}"
    assert rep["tenants"]["quota_shed_requests"] >= 1

    classic = WorkloadSpec(num_requests=6, seed=11, vocab_size=128,
                           prompt_len=(4, 10), output_len=(3, 6))
    clock = VirtualClock()
    eng = LLMEngine(tiny_model, now_fn=clock.now, **ENG)
    res = Driver(eng, clock, step_time_s=0.02).run(classic.compile())
    crep = json.loads(report_json(build_report(res, spec=classic,
                                               trace=classic.compile())))
    assert "tenants" not in crep


# ---------------------------------------------------------------------------
# observability: tenant attribution on spans, classic traces unmoved


def test_tenant_id_rides_spans_and_outputs(tiny_model):
    """tenant_id travels Request -> RequestOutput -> trace spans; the
    attribution key appears ONLY when set, so classic (tenantless)
    span details stay byte-identical to the pre-tenancy schema."""
    tracer = RequestTracer()
    eng = LLMEngine(tiny_model, tracer=tracer,
                    tenants=[{"tenant_id": "a"}], **ENG)
    rt = eng.add_request(_prompt(4), max_new_tokens=3, tenant_id="a")
    rc = eng.add_request(_prompt(4, seed=2), max_new_tokens=3)
    outs = eng.run(max_steps=100)
    assert outs[rt].tenant_id == "a"
    assert outs[rc].tenant_id is None
    t_kinds = {k: d for _, k, d in tracer.spans(rt)}
    assert t_kinds["admission"]["tenant"] == "a"
    assert t_kinds["finish"]["tenant"] == "a"
    for _, k, d in tracer.spans(rc):
        assert "tenant" not in (d or {}), \
            f"classic span {k} grew a tenant key"


# ---------------------------------------------------------------------------
# tune -> serve bridge


def test_tuner_masked_fused_training_and_publish(tiny_model):
    """AdapterTuner trains only the LoRA factors over the frozen base
    through the MASKED fused-optimizer path (pinned loss trajectory —
    drift means the masked branch or the adapter forward changed), and
    publish() hot-adds the tuned factors into a live engine."""
    from paddle_tpu.models.generation import extract_params
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            intermediate_size=64, num_attention_heads=4,
                            num_key_value_heads=2, vocab_size=97)
    model = LlamaForCausalLM(cfg)
    tuner = AdapterTuner(extract_params(model), cfg, rank=4, seed=0,
                         lr=5e-2)
    ids = np.random.default_rng(0).integers(0, 97, (2, 12))
    losses = [tuner.step(ids) for _ in range(6)]
    assert np.allclose(
        losses, [4.5451, 4.514, 4.4644, 4.4378, 4.4244, 4.4152],
        atol=2e-3), losses
    assert losses[-1] < losses[0], "tuning must reduce the loss"
    # the masked-branch witness: frozen projections ride the SAME
    # fused buckets with zero-masked updates, never a bucket rebuild
    assert any(b.masks for b in tuner.opt._fused_engine.buckets), \
        "the train subset must hit the masked fused path"

    eng = LLMEngine(model, adapter_slots=2, adapter_rank=4, **ENG)
    rb = eng.add_request(_prompt(5, v=97), max_new_tokens=4)
    base = eng.run(max_steps=100)[rb].token_ids
    tuner.publish(eng.adapters, "tuned")
    rt = eng.add_request(_prompt(5, v=97), max_new_tokens=4,
                         adapter_id="tuned")
    out = eng.run(max_steps=100)[rt]
    assert out.status == "finished"
    assert len(out.token_ids) == len(base) == 4
    assert eng.decode_cache_size() == 1
    # the tuned delta round-trips the slab bit-exactly
    got = eng.adapters.get("tuned")
    want = tuner.export()
    for p in ("q", "v"):
        np.testing.assert_array_equal(got[p][0], want[p][0])
        np.testing.assert_array_equal(got[p][1], want[p][1])
