"""Capability-tail parity (round-2 verdict item #10): fractional pooling,
1-D/3-D unpool, RNN-T loss, int4 weight packing, multivariate/structured
distributions, and the widened flag registry."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def T(x, dtype=np.float32):
    return paddle.to_tensor(np.asarray(x, dtype))


class TestFractionalPool:
    def test_reference_doc_example(self):
        """pooling.py:2118: seq [2,4,3,1,5,2,3], output 5, u=0.3 ->
        [2,4,1,5,3] (alpha=1.4, starts [0,1,3,4,6], ends [1,3,4,6,7])."""
        x = T([2, 4, 3, 1, 5, 2, 3]).reshape([1, 1, 1, 7])
        out = F.fractional_max_pool2d(x, output_size=(1, 5), random_u=0.3)
        np.testing.assert_allclose(
            np.asarray(out.numpy()).ravel(), [2, 4, 1, 5, 3])

    def test_2d_with_kernel_and_mask(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        out, mask = F.fractional_max_pool2d(
            T(x), output_size=4, kernel_size=2, random_u=0.5,
            return_mask=True)
        assert tuple(out.shape) == (2, 3, 4, 4)
        # mask holds flat h*w positions whose values match the outputs
        o = np.asarray(out.numpy())
        m = np.asarray(mask.numpy())
        flat = x.reshape(2, 3, 64)
        np.testing.assert_allclose(
            np.take_along_axis(flat, m.reshape(2, 3, 16), -1),
            o.reshape(2, 3, 16))

    def test_3d(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, 2, 6, 6, 6)).astype(np.float32)
        out = F.fractional_max_pool3d(T(x), output_size=3, random_u=0.4)
        assert tuple(out.shape) == (1, 2, 3, 3, 3)
        # every output is the max of SOME input window: must appear in x
        o = np.asarray(out.numpy())
        assert np.isin(o, x).all()

    def test_random_u_drawn_from_global_rng(self):
        x = T(np.random.default_rng(3).standard_normal((1, 1, 8, 8)))
        paddle.seed(11)
        a = np.asarray(F.fractional_max_pool2d(x, 3).numpy())
        paddle.seed(11)
        b = np.asarray(F.fractional_max_pool2d(x, 3).numpy())
        np.testing.assert_array_equal(a, b)


class TestUnpool:
    def test_unpool1d_roundtrip(self):
        x = T([[1, 9, 2, 8, 3, 7, 4, 6]]).reshape([1, 1, 8])
        out, idx = F.max_pool1d(x, 2, stride=2, return_mask=True)
        rec = F.max_unpool1d(out, idx, 2, stride=2)
        exp = np.zeros((1, 1, 8), np.float32)
        exp[0, 0, [1, 3, 5, 7]] = [9, 8, 7, 6]
        np.testing.assert_allclose(np.asarray(rec.numpy()), exp)

    def test_unpool3d_roundtrip(self):
        rng = np.random.default_rng(0)
        x = T(rng.standard_normal((2, 2, 4, 4, 4)))
        out, idx = F.max_pool3d(x, 2, stride=2, return_mask=True)
        rec = F.max_unpool3d(out, idx, 2, stride=2)
        assert tuple(rec.shape) == (2, 2, 4, 4, 4)
        # pooled maxima land back at their argmax positions
        r = np.asarray(rec.numpy())
        o = np.asarray(out.numpy())
        assert np.isclose(np.sort(r[r != 0]).ravel(),
                          np.sort(o.ravel())).all()


class TestRnntLoss:
    def _oracle(self, logits, labels, t_len, u_len, blank):
        """Plain numpy forward DP over the (T, U) lattice."""
        b = logits.shape[0]
        out = np.zeros(b, np.float64)
        for i in range(b):
            tl, ul = int(t_len[i]), int(u_len[i])
            lp = logits[i] - np.log(
                np.exp(logits[i]).sum(-1, keepdims=True))
            alpha = np.full((tl, ul + 1), -np.inf)
            for t in range(tl):
                for u in range(ul + 1):
                    cands = []
                    if t == 0 and u == 0:
                        alpha[0, 0] = 0.0
                        continue
                    if t > 0:
                        cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
                    if u > 0:
                        cands.append(alpha[t, u - 1]
                                     + lp[t, u - 1, labels[i, u - 1]])
                    alpha[t, u] = np.logaddexp.reduce(cands)
            out[i] = -(alpha[tl - 1, ul] + lp[tl - 1, ul, blank])
        return out

    def test_parity_with_numpy_dp(self):
        rng = np.random.default_rng(0)
        b, t, u, v = 3, 6, 4, 5
        logits = rng.standard_normal((b, t, u + 1, v)).astype(np.float32)
        labels = rng.integers(1, v, (b, u)).astype(np.int64)
        t_len = np.asarray([6, 5, 4], np.int64)
        u_len = np.asarray([4, 3, 2], np.int64)
        got = F.rnnt_loss(T(logits), paddle.to_tensor(labels),
                          paddle.to_tensor(t_len), paddle.to_tensor(u_len),
                          blank=0, fastemit_lambda=0.0, reduction="none")
        exp = self._oracle(logits, labels, t_len, u_len, 0)
        np.testing.assert_allclose(np.asarray(got.numpy()), exp, rtol=1e-4)

    def _grads(self, logits_np, labels, tl, ul, lam):
        logits = T(logits_np)
        logits.stop_gradient = False
        loss = F.rnnt_loss(logits, paddle.to_tensor(labels),
                           paddle.to_tensor(tl), paddle.to_tensor(ul),
                           fastemit_lambda=lam, reduction="sum")
        loss.backward()
        return float(loss.numpy()), np.asarray(logits.grad.numpy())

    @pytest.mark.slow
    def test_fastemit_scales_gradients_not_loss(self):
        """warp-transducer FastEmit semantics: the loss VALUE is the plain
        transducer NLL; lambda scales the EMIT-transition gradient."""
        rng = np.random.default_rng(1)
        logits = rng.standard_normal((2, 4, 3, 4)).astype(np.float32)
        labels = np.asarray([[1, 2], [2, 3]], np.int64)
        tl = np.asarray([4, 4], np.int64)
        ul = np.asarray([2, 2], np.int64)
        l0, g0 = self._grads(logits, labels, tl, ul, 0.0)
        l1, g1 = self._grads(logits, labels, tl, ul, 0.5)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)   # value unchanged
        assert np.isfinite(g0).all() and np.isfinite(g1).all()
        assert not np.allclose(g0, g1)                  # grads DO change
        # numeric check of the lambda=0 gradient against finite differences
        eps = 1e-3
        i = (0, 1, 1, 2)
        bumped = logits.copy()
        bumped[i] += eps
        lp, _ = self._grads(bumped, labels, tl, ul, 0.0)
        bumped[i] -= 2 * eps
        lm, _ = self._grads(bumped, labels, tl, ul, 0.0)
        np.testing.assert_allclose(g0[i], (lp - lm) / (2 * eps),
                                   rtol=2e-2, atol=2e-4)


class TestInt4:
    def test_pack_unpack_roundtrip(self):
        from paddle_tpu.quantization import quantize_to_int4, unpack_int4
        rng = np.random.default_rng(0)
        w = T(rng.standard_normal((7, 6)))     # odd rows exercise padding
        packed, scale = quantize_to_int4(w, axis=1)
        assert packed.shape == (4, 6) and packed.dtype == np.int8
        vals = np.asarray(unpack_int4(packed, 7))
        assert vals.shape == (7, 6)
        assert np.abs(vals).max() <= 7
        np.testing.assert_allclose(vals * np.asarray(scale),
                                   np.asarray(w.numpy()), atol=np.asarray(
                                       scale).max() / 2 + 1e-6)

    def test_int4_linear_close_and_eighth_memory(self):
        from paddle_tpu.quantization import Int4Linear
        paddle.seed(0)
        lin = paddle.nn.Linear(16, 8)
        q = Int4Linear(lin)
        x = T(np.random.default_rng(1).standard_normal((4, 16)))
        ref = np.asarray(lin(x).numpy())
        got = np.asarray(q(x).numpy())
        # int4 is lossy; relative error should still be moderate
        assert np.abs(got - ref).mean() < 0.12 * np.abs(ref).mean() + 0.05
        assert q.w_packed.size * 1 == 8 * 8   # 16x8 fp32 -> 8x8 bytes

    def test_quantize_for_inference_int4_mode(self):
        from paddle_tpu.quantization import quantize_for_inference, Int4Linear
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 8), paddle.nn.ReLU())
        q = quantize_for_inference(m, mode="weight_only_int4")
        assert isinstance(q[0], Int4Linear)


class TestDistributionsTail:
    def test_multivariate_normal(self):
        mu = np.asarray([1.0, -1.0], np.float32)
        cov = np.asarray([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = paddle.distribution.MultivariateNormal(
            T(mu), covariance_matrix=T(cov))
        x = np.asarray([[0.0, 0.0], [1.0, -1.0]], np.float32)
        lp = np.asarray(d.log_prob(T(x)).numpy())
        # scipy-free oracle
        inv = np.linalg.inv(cov)
        det = np.linalg.det(cov)
        for i in range(2):
            v = x[i] - mu
            exp = -0.5 * v @ inv @ v - 0.5 * np.log(
                (2 * np.pi) ** 2 * det)
            np.testing.assert_allclose(lp[i], exp, rtol=1e-5)
        ent = float(d.entropy().numpy())
        np.testing.assert_allclose(
            ent, 0.5 * np.log((2 * np.pi * np.e) ** 2 * det), rtol=1e-5)
        paddle.seed(0)
        s = np.asarray(d.sample((20000,)).numpy())
        np.testing.assert_allclose(s.mean(0), mu, atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)

    def test_continuous_bernoulli(self):
        d = paddle.distribution.ContinuousBernoulli(
            T([0.2, 0.5, 0.9]))
        lp = np.asarray(d.log_prob(T([0.5, 0.5, 0.5])).numpy())
        assert np.isfinite(lp).all()
        # density integrates to ~1 (midpoint rule)
        grid = np.linspace(0.0, 1.0, 2001, dtype=np.float32)
        for p in (0.2, 0.5, 0.9):
            dd = paddle.distribution.ContinuousBernoulli(T([p]))
            vals = np.exp(np.asarray(
                dd.log_prob(T(grid).reshape([-1, 1])).numpy())).ravel()
            assert abs(np.trapezoid(vals, grid) - 1.0) < 2e-3, p
        paddle.seed(1)
        s = np.asarray(d.sample((4000,)).numpy())
        assert ((s >= 0) & (s <= 1)).all()
        np.testing.assert_allclose(s.mean(0),
                                   np.asarray(d.mean.numpy()), atol=0.03)

    def test_lkj_cholesky(self):
        paddle.seed(2)
        d = paddle.distribution.LKJCholesky(4, concentration=2.0)
        L = np.asarray(d.sample().numpy())
        assert L.shape == (4, 4)
        corr = L @ L.T
        np.testing.assert_allclose(np.diag(corr), 1.0, atol=1e-5)
        assert (np.linalg.eigvalsh(corr) > 0).all()
        assert np.isfinite(float(d.log_prob(T(L)).numpy()))


def test_flag_registry_breadth():
    from paddle_tpu.core.flags import GLOBAL_FLAGS
    assert len(GLOBAL_FLAGS.all()) >= 50
    # reference names resolve through paddle.set_flags/get_flags
    paddle.set_flags({"FLAGS_use_autotune": False})
    assert paddle.get_flags("use_autotune")["FLAGS_use_autotune"] is False
    paddle.set_flags({"FLAGS_use_autotune": True})
    assert "FLAGS_nccl_blocking_wait" in paddle.get_flags(
        "nccl_blocking_wait")


@pytest.mark.slow
def test_vision_layer_wrappers():
    """DeformConv2D/RoIAlign/RoIPool/PSRoIPool Layer forms (reference:
    vision/ops.py class forms over the functional zoo)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.vision.ops as vo

    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((1, 4, 8, 8)).astype("float32"))
    boxes = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
    bn = paddle.to_tensor(np.array([1], np.int32))
    assert vo.RoIAlign(2)(x, boxes, bn).shape == [1, 4, 2, 2]
    assert vo.RoIPool(2)(x, boxes, bn).shape == [1, 4, 2, 2]
    assert vo.PSRoIPool(2)(x, boxes, bn).shape == [1, 1, 2, 2]
    dc = vo.DeformConv2D(4, 6, 3, padding=1)
    off = paddle.zeros([1, 18, 8, 8])
    out = dc(x, off)
    assert out.shape == [1, 6, 8, 8]
    # parity with the functional form at zero offsets
    ref = vo.deform_conv2d(x, off, dc.weight, dc.bias, padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)
    out.sum().backward()
    assert dc.weight.grad is not None


def test_linalg_inv_and_svd_lowrank():
    import numpy as np

    import paddle_tpu as paddle

    a = np.random.default_rng(0).standard_normal((5, 5)).astype("float32")
    inv = paddle.linalg.inv(paddle.to_tensor(a)).numpy()
    np.testing.assert_allclose(inv @ a, np.eye(5), atol=1e-4)
    x = np.random.default_rng(1).standard_normal((20, 8)).astype("float32")
    u, s, v = paddle.linalg.svd_lowrank(paddle.to_tensor(x), q=8)
    rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(rec, x, atol=1e-3)
    # M subtraction path
    m = np.ones_like(x)
    u2, s2, v2 = paddle.linalg.svd_lowrank(paddle.to_tensor(x),
                                           q=8, M=paddle.to_tensor(m))
    rec2 = u2.numpy() @ np.diag(s2.numpy()) @ v2.numpy().T
    np.testing.assert_allclose(rec2, x - m, atol=1e-3)
