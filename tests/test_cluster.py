"""Cluster-scale serving under failure (serving/cluster.py,
serving/faults.py, loadgen/cluster.py) — the ISSUE-11 acceptance bars,
asserted not logged:

- a seeded kill-one-of-three-replicas loadgen run completes every
  non-shed request with greedy outputs token-identical to a no-fault
  single-engine run of the same trace, and the cluster report
  (retry/degradation counters included) is byte-reproducible per seed;
- the degradation ladder engages and fully restores (hysteresis) under
  a flash-crowd injection, with each transition visible in
  ``metrics_snapshot()`` and the loadgen report;
- the replica lifecycle state machine (HEALTHY -> DEGRADED -> DRAINING
  -> DOWN -> RECOVERING) behaves under each injected fault kind, retry
  exhaustion converts to a structured shed (never a hang), and routing
  (session affinity + power-of-two-choices) steers work off sick
  replicas.
"""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.loadgen import (ClusterDriver, Driver, VirtualClock,
                                WorkloadSpec, build_cluster_report,
                                report_json, trace_fingerprint)
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import (ClusterEngine, DegradationLadder,
                                FaultEvent, FaultSchedule, LLMEngine,
                                ReplicaState)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=64,
                            intermediate_size=128, num_attention_heads=2,
                            num_key_value_heads=2, vocab_size=128)
    return LlamaForCausalLM(cfg)


ENGINE_KW = dict(max_len=32, page_size=4)


def _cluster(model, clock, n=3, **kw):
    merged = {**ENGINE_KW, **kw}
    return ClusterEngine(model, n, seed=0, now_fn=clock.now, **merged)


# ---------------------------------------------------------------------------
# acceptance: kill one of three replicas, token identity + byte identity
# ---------------------------------------------------------------------------

_KILL = WorkloadSpec(num_requests=30, seed=3, arrival="poisson",
                     arrival_rate=120.0, prompt_len=(4, 12),
                     output_len=(4, 8), slo_e2e_s=2.0, vocab_size=128)
_KILL_FAULTS = FaultSchedule([
    FaultEvent(t=0.06, replica=1, kind="crash", recover_s=0.1)])


def _kill_run(model):
    clock = VirtualClock()
    cluster = _cluster(model, clock, retry_budget=2, faults=_KILL_FAULTS)
    trace = _KILL.compile()
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(trace)
    report = build_cluster_report(result, spec=_KILL, trace=trace,
                                  faults=_KILL_FAULTS)
    return cluster, result, report


def test_kill_one_of_three_is_token_identical_to_single_engine(tiny_model):
    """THE acceptance gate: greedy outputs under a mid-run replica kill
    must match a fault-free single-engine run of the same trace token
    for token — requeued requests re-prefill on a survivor and
    regenerate the identical continuation."""
    trace = _KILL.compile()
    clock = VirtualClock()
    eng = LLMEngine(tiny_model, now_fn=clock.now, seed=0, **ENGINE_KW)
    Driver(eng, clock, step_time_s=0.01).run(trace)
    ref = {rid: o.token_ids for rid, o in eng.outputs().items()}

    cluster, result, report = _kill_run(tiny_model)
    assert report["cluster"]["crashes"] == 1
    assert report["cluster"]["retries"] >= 1, \
        "the kill must have requeued in-flight work"
    assert report["cluster"]["recoveries"] == 1, \
        "the killed replica must have come back"
    assert report["requests"]["unresolved"] == 0
    outs = cluster.outputs()
    for rid, toks in ref.items():
        assert outs[rid].status == "finished", \
            f"{rid}: {outs[rid].status} ({outs[rid].finish_reason})"
        assert outs[rid].token_ids == toks, \
            f"{rid} diverged from the fault-free single engine"
    # retried requests genuinely exist and are recorded per-request
    assert any(r.num_retries > 0 for r in result.records)
    # every live pool was audited every step, none over-allocated
    assert result.invariant_checks > 0
    assert report["kv_pressure"]["over_allocated"] is False


def test_kill_run_report_is_byte_reproducible(tiny_model):
    _, _, r1 = _kill_run(tiny_model)
    _, _, r2 = _kill_run(tiny_model)
    assert report_json(r1) == report_json(r2), \
        "same seeds + same fault script must reproduce the report bytes"
    # the fault script itself is part of the artifact
    assert r1["cluster"]["faults"][0]["kind"] == "crash"
    assert r1["cluster"]["time_in_state_s"].get("down", 0.0) > 0.0


# ---------------------------------------------------------------------------
# acceptance: degradation ladder engages and restores under a flash crowd
# ---------------------------------------------------------------------------

def test_degradation_ladder_flash_crowd_engages_and_restores(tiny_model):
    """A flash-crowd arrival spike on a deliberately small pool must
    climb the ladder (>= 1 escalation), every transition must be
    visible in metrics_snapshot() and the report, and after the crowd
    passes the ladder must fully restore (hysteresis) — level 0,
    restorations == escalations."""
    spec = WorkloadSpec(num_requests=40, seed=11, arrival="flash_crowd",
                        arrival_rate=20.0, flash_at_s=0.3,
                        flash_duration_s=0.5, flash_multiplier=20.0,
                        prompt_len=(4, 12), output_len=(4, 8),
                        slo_e2e_s=10.0, vocab_size=128)
    # a calm tail keeps the cluster stepping after the crowd passes, so
    # the ladder's hysteretic restore is observable inside the run
    tail = WorkloadSpec(num_requests=10, seed=12, arrival="deterministic",
                        arrival_rate=10.0, prompt_len=(4, 8),
                        output_len=(3, 5), slo_e2e_s=10.0, vocab_size=128)

    def trace_of():
        crowd = spec.compile()
        last = max(r.arrival_s for r in crowd)
        return crowd + [dataclasses.replace(r, arrival_s=r.arrival_s
                                            + last + 1.0)
                        for r in tail.compile()]

    ladder_kw = dict(engage_after=2, restore_after=2,
                     queue_age_slo_s=0.2)
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=1, num_pages=33,
                       max_num_seqs=4, ladder_kw=ladder_kw)
    trace = trace_of()
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(trace)
    report = build_cluster_report(result, spec=spec, trace=trace)
    assert report["requests"]["unresolved"] == 0
    deg = report["cluster"]["degradation"]
    assert deg["escalations"] >= 1, "the flash crowd must engage the ladder"
    assert deg["restorations"] == deg["escalations"], \
        "the ladder must fully restore once pressure clears"
    assert deg["final_levels"] == [0]
    assert report["cluster"]["time_degraded_s"] > 0.0
    # the transitions are visible on the replica's own metrics too
    snap = cluster.replicas[0].engine.metrics_snapshot()
    assert snap["degradation_escalations"] == deg["escalations"]
    assert snap["degradation_restorations"] == deg["restorations"]
    assert snap["degradation_level"] == 0
    # and the report reproduces byte for byte
    clock2 = VirtualClock()
    cluster2 = _cluster(tiny_model, clock2, n=1, num_pages=33,
                        max_num_seqs=4, ladder_kw=ladder_kw)
    result2 = ClusterDriver(cluster2, clock2, step_time_s=0.01).run(
        trace_of())
    assert report_json(build_cluster_report(result2, spec=spec,
                                            trace=trace_of())) \
        == report_json(report)


def test_ladder_rungs_shed_and_restore_engine_knobs(tiny_model):
    """Standalone ladder semantics: rungs flip the engine's runtime
    knobs in shed order and restore them in reverse, hysteretically."""
    # a starved pool pauses admission at the watermark, so the waiting
    # queue AGES — sustained queue-age pressure the ladder must answer
    clock = VirtualClock()
    eng = LLMEngine(tiny_model, max_len=32, page_size=4, num_pages=9,
                    max_num_seqs=4, burst_tokens=4, pinned_prefix_pages=2,
                    now_fn=clock.now)
    ladder = DegradationLadder(eng, engage_after=1, restore_after=2,
                               queue_age_slo_s=0.02)
    orig_hw = eng.pool.high_watermark
    orig_mpps = eng.scheduler.config.max_prefills_per_step
    for i in range(6):
        eng.add_request([1 + i, 2, 3, 4, 5, 6, 7, 8], max_new_tokens=16)
    steps = 0
    while eng.has_unfinished():
        clock.advance(0.01)
        eng.step()
        ladder.observe()
        steps += 1
        assert steps < 300
    assert eng.metrics.degradation_escalations.value >= 1
    # drain: pressure gone, ladder must walk all the way back down
    for _ in range(4 * 2 * len(DegradationLadder.RUNGS)):
        ladder.observe()
    assert ladder.level == 0
    assert eng.metrics.degradation_restorations.value == \
        eng.metrics.degradation_escalations.value
    assert eng.spec_enabled is True
    assert eng.burst_tokens == 4
    assert eng.pool.high_watermark == orig_hw
    assert eng.scheduler.config.max_prefills_per_step == orig_mpps
    assert eng.metrics.degradation_level.value == 0


# ---------------------------------------------------------------------------
# state machine under each fault kind
# ---------------------------------------------------------------------------

def test_drain_blocks_admission_requeues_waiting_and_recovers(tiny_model):
    """DRAINING: waiting work moves to survivors, running rows finish in
    place, no new admissions for the window, then the replica returns."""
    spec = WorkloadSpec(num_requests=24, seed=5, arrival="poisson",
                        arrival_rate=200.0, prompt_len=(4, 10),
                        output_len=(4, 8), slo_e2e_s=5.0, vocab_size=128)
    faults = FaultSchedule([
        FaultEvent(t=0.05, replica=0, kind="drain", duration_s=0.2)])
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2, max_num_seqs=2,
                       retry_budget=3, faults=faults)
    trace = spec.compile()
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(trace)
    report = build_cluster_report(result, spec=spec, trace=trace,
                                  faults=faults)
    assert report["cluster"]["drains"] == 1
    assert report["cluster"]["time_in_state_s"].get("draining", 0) > 0.0
    assert report["requests"]["unresolved"] == 0
    # everything completed despite the drain window
    assert report["requests"]["finished"] == 24
    assert cluster.replicas[0].state is ReplicaState.HEALTHY
    assert cluster.replicas[0].engine.scheduler.admission_blocked is False


def test_slowdown_shifts_routing_away_from_the_sick_replica(tiny_model):
    """A slowed replica's health score (consecutive-step latency
    multiplier off the cluster's observation layer) must push
    power-of-two-choices admission onto its peers."""
    spec = WorkloadSpec(num_requests=30, seed=9, arrival="poisson",
                        arrival_rate=60.0, prompt_len=(4, 10),
                        output_len=(3, 6), slo_e2e_s=5.0, vocab_size=128)
    faults = FaultSchedule([
        FaultEvent(t=0.0, replica=0, kind="slowdown", duration_s=10.0,
                   magnitude=4.0)])
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=3, faults=faults)
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(
        spec.compile())
    assert build_cluster_report(result)["requests"]["unresolved"] == 0
    counts = {r.rid: 0 for r in cluster.replicas}
    for meta in cluster._meta.values():
        counts[meta["replica"]] += 1
    assert counts[0] < counts[1] and counts[0] < counts[2], (
        f"routing must avoid the 4x-slowed replica: {counts}")
    # the slowed replica really ran fewer engine steps per cluster round
    assert cluster.replicas[0].steps < cluster.replicas[1].steps


def test_flaky_steps_are_absorbed_then_escalate_to_crash(tiny_model):
    """A short flaky window is transient (counted, survived); a long one
    crosses crash_after_flaky and escalates to a crash + recovery."""
    spec = WorkloadSpec(num_requests=12, seed=2, arrival="deterministic",
                        arrival_rate=100.0, prompt_len=(4, 8),
                        output_len=(4, 6), slo_e2e_s=5.0, vocab_size=128)
    # short window: 2 flaky rounds < crash_after_flaky=5
    faults = FaultSchedule([
        FaultEvent(t=0.03, replica=0, kind="flaky", duration_s=0.02)])
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2, faults=faults,
                       crash_after_flaky=5)
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(
        spec.compile())
    rep = build_cluster_report(result)
    assert rep["cluster"]["flaky_steps"] >= 1
    assert rep["cluster"]["crashes"] == 0
    assert rep["requests"]["unresolved"] == 0

    # long window: escalates after crash_after_flaky consecutive raises
    faults2 = FaultSchedule([
        FaultEvent(t=0.03, replica=0, kind="flaky", duration_s=1.0)])
    clock2 = VirtualClock()
    cluster2 = _cluster(tiny_model, clock2, n=2, faults=faults2,
                        crash_after_flaky=3, crash_recover_s=0.2,
                        retry_budget=3)
    result2 = ClusterDriver(cluster2, clock2, step_time_s=0.01).run(
        spec.compile())
    rep2 = build_cluster_report(result2)
    assert rep2["cluster"]["flaky_steps"] >= 3
    assert rep2["cluster"]["crashes"] == 1
    assert rep2["requests"]["unresolved"] == 0


def test_kv_pressure_fault_pressures_the_pool_then_releases(tiny_model):
    """The ballast must create REAL watermark pressure (visible in peak
    utilization and the ladder) for its window and release after it."""
    spec = WorkloadSpec(num_requests=16, seed=4, arrival="poisson",
                        arrival_rate=100.0, prompt_len=(4, 10),
                        output_len=(4, 8), slo_e2e_s=5.0, vocab_size=128)
    faults = FaultSchedule([
        FaultEvent(t=0.02, replica=0, kind="kv_pressure", duration_s=0.3,
                   magnitude=0.7)])
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=1, num_pages=33,
                       max_num_seqs=4, faults=faults,
                       ladder_kw=dict(engage_after=2, restore_after=4))
    result = ClusterDriver(cluster, clock, step_time_s=0.01).run(
        spec.compile())
    rep = build_cluster_report(result)
    assert rep["cluster"]["kv_pressure_faults"] == 1
    assert rep["kv_pressure"]["peak_page_utilization"] >= 0.7
    assert rep["requests"]["unresolved"] == 0
    # the run may drain inside the fault window — tick the cluster past
    # the window's end and the ballast must release
    clock.advance_to(0.5)
    cluster.step()
    pool = cluster.replicas[0].engine.pool
    assert cluster.replicas[0].ballast_id not in pool, \
        "the ballast must release at the window's end"
    assert pool.free_pages == pool.capacity
    pool.check_invariants()


def test_retry_budget_exhaustion_is_a_structured_shed(tiny_model):
    """retry_budget=0 + an unrecoverable crash: the dead replica's
    in-flight requests convert to terminal shed outputs with reason
    retries_exhausted — never a hang."""
    spec = WorkloadSpec(num_requests=18, seed=6, arrival="poisson",
                        arrival_rate=150.0, prompt_len=(4, 10),
                        output_len=(6, 10), slo_e2e_s=5.0, vocab_size=128)
    faults = FaultSchedule([
        FaultEvent(t=0.05, replica=1, kind="crash")])   # never recovers
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2, retry_budget=0,
                       faults=faults)
    result = ClusterDriver(cluster, clock, step_time_s=0.01,
                           max_steps=5000).run(spec.compile())
    rep = build_cluster_report(result)
    assert rep["requests"]["unresolved"] == 0, "no hangs, ever"
    assert rep["cluster"]["retry_budget_sheds"] >= 1
    assert rep["cluster"]["retries"] == 0
    shed = [r for r in result.records if r.status == "shed"]
    assert shed and all(r.finish_reason == "retries_exhausted"
                        for r in shed)
    assert cluster.replicas[1].state is ReplicaState.DOWN


def test_session_affinity_keeps_cohorts_on_one_replica(tiny_model):
    """Requests sharing a prefix cohort carry a session id; with no
    faults, a cohort's requests must all land on ONE replica (whose
    prefix cache then serves them)."""
    spec = WorkloadSpec(num_requests=30, seed=8, arrival="poisson",
                        arrival_rate=80.0, prompt_len=(6, 14),
                        output_len=(2, 5), shared_prefix_fraction=0.6,
                        shared_prefix_len=5, num_shared_prefixes=2,
                        slo_e2e_s=5.0, vocab_size=128)
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=3)
    trace = spec.compile()
    ClusterDriver(cluster, clock, step_time_s=0.01).run(trace)
    by_cohort = {}
    for r in trace:
        if r.prefix_cohort >= 0:
            by_cohort.setdefault(r.prefix_cohort, set()).add(
                cluster._meta[r.request_id]["replica"])
    assert by_cohort, "the 0.6 mix must produce cohort traffic"
    for cohort, replicas in by_cohort.items():
        assert len(replicas) == 1, \
            f"cohort {cohort} scattered across replicas {replicas}"
    assert cluster.counters["affinity_hits"] > 0


# ---------------------------------------------------------------------------
# fault schedule + workload shape plumbing
# ---------------------------------------------------------------------------

def test_fault_event_validation():
    with pytest.raises(ValueError, match="kind"):
        FaultEvent(t=0.0, replica=0, kind="meteor")
    with pytest.raises(ValueError, match="duration_s"):
        FaultEvent(t=0.0, replica=0, kind="drain")
    with pytest.raises(ValueError, match="recover_s"):
        FaultEvent(t=0.0, replica=0, kind="crash", recover_s=-1.0)
    with pytest.raises(ValueError, match="multiplier"):
        FaultEvent(t=0.0, replica=0, kind="slowdown", duration_s=1.0,
                   magnitude=0.5)
    with pytest.raises(ValueError, match="fraction"):
        FaultEvent(t=0.0, replica=0, kind="kv_pressure", duration_s=1.0,
                   magnitude=1.5)
    with pytest.raises(TypeError):
        FaultSchedule(["crash"])


def test_fault_schedule_generate_is_seeded_and_sorted():
    s1 = FaultSchedule.generate(seed=5, num_replicas=3, horizon_s=2.0)
    s2 = FaultSchedule.generate(seed=5, num_replicas=3, horizon_s=2.0)
    assert s1.describe() == s2.describe()
    assert len(s1) == 6
    ts = [e.t for e in s1]
    assert ts == sorted(ts)
    s3 = FaultSchedule.generate(seed=6, num_replicas=3, horizon_s=2.0)
    assert s3.describe() != s1.describe()


def test_arrival_shapes_compile_deterministically():
    flash = WorkloadSpec(num_requests=60, seed=1, arrival="flash_crowd",
                         arrival_rate=10.0, flash_at_s=1.0,
                         flash_duration_s=2.0, flash_multiplier=10.0)
    t1, t2 = flash.compile(), flash.compile()
    assert trace_fingerprint(t1) == trace_fingerprint(t2)
    # the flash window compresses inter-arrival gaps ~10x
    arrivals = [r.arrival_s for r in t1]
    gaps_in = [b - a for a, b in zip(arrivals, arrivals[1:])
               if 1.0 <= a < 3.0]
    gaps_out = [b - a for a, b in zip(arrivals, arrivals[1:])
                if a < 1.0 or a >= 3.0]
    assert gaps_in and gaps_out
    assert np.mean(gaps_in) < np.mean(gaps_out) / 3.0

    diurnal = WorkloadSpec(num_requests=40, seed=1, arrival="diurnal",
                           arrival_rate=10.0, rate_period_s=4.0,
                           rate_amplitude=0.9)
    d1 = diurnal.compile()
    assert trace_fingerprint(d1) == \
        trace_fingerprint(diurnal.compile())
    assert trace_fingerprint(d1) != trace_fingerprint(t1)
    with pytest.raises(ValueError, match="rate_amplitude"):
        WorkloadSpec(arrival="diurnal", rate_amplitude=1.0)
    with pytest.raises(ValueError, match="flash_multiplier"):
        WorkloadSpec(arrival="flash_crowd", flash_multiplier=0.5)


def test_cluster_driver_rejects_mismatched_clock(tiny_model):
    clock = VirtualClock()
    cluster = ClusterEngine(tiny_model, 1, **ENGINE_KW)   # wall clock
    with pytest.raises(ValueError, match="now_fn"):
        ClusterDriver(cluster, clock)


def test_cluster_add_request_rejects_oversize_like_engine(tiny_model):
    from paddle_tpu.serving import RequestRejected
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2)
    with pytest.raises(RequestRejected):
        cluster.add_request(list(range(30)), max_new_tokens=30,
                            request_id="huge")
    assert cluster.outputs()["huge"].status == "aborted"
    assert cluster.outputs()["huge"].finish_reason == "rejected_oversize"
    assert not cluster.has_unfinished()


def test_invalid_request_finalizes_structured_never_hangs(tiny_model):
    """Engine-side parameter validation (empty prompt here) must not
    leave a permanently-unfinished cluster output: the synchronous path
    re-raises AFTER finalizing, and a parked invalid request becomes a
    structured abort at step time instead of crashing the fleet round."""
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2)
    with pytest.raises(ValueError):
        cluster.add_request([], request_id="bad-sync")
    out = cluster.outputs()["bad-sync"]
    assert out.status == "aborted"
    assert out.finish_reason == "invalid_request"
    assert not cluster.has_unfinished()
    # parked path: no replica admittable at add time, so the invalid
    # request parks silently and must resolve structurally at step()
    for rep in cluster.replicas:
        cluster._set_state(rep, ReplicaState.DRAINING, clock.now())
    cluster.add_request([], request_id="bad-parked")
    assert cluster.outputs()["bad-parked"].status == "pending"
    for rep in cluster.replicas:
        cluster._set_state(rep, ReplicaState.HEALTHY, clock.now())
        rep.engine.scheduler.admission_blocked = False
    clock.advance(0.01)
    cluster.step()
    out = cluster.outputs()["bad-parked"]
    assert out.status == "aborted"
    assert out.finish_reason == "invalid_request"
    assert not cluster.has_unfinished()


def test_requeued_request_keeps_lifetime_preemption_count(tiny_model):
    """num_preemptions on the cluster output is the LIFETIME count:
    preemptions charged by a replica that later crashed must survive
    the requeue instead of resetting with the new assignment."""
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2)
    rid = cluster.add_request(list(range(4)), max_new_tokens=4)
    meta = cluster._meta[rid]
    # simulate two preemptions observed on the first replica, then a
    # requeue (the crash path calls _requeue exactly like this)
    cluster._outputs[rid].num_preemptions = 2
    meta["replica"] = None
    cluster._requeue(rid, clock.now(), {})
    assert meta["preempt_base"] == 2
    # absorb a replica-side output carrying 1 fresh preemption
    rep = cluster.replicas[0]
    meta["replica"] = rep.rid
    fresh = type(cluster._outputs[rid])(
        rid, list(range(4)), status="running")
    fresh.num_preemptions = 1
    cluster._absorb(rep, fresh, {})
    assert cluster._outputs[rid].num_preemptions == 3


def test_exhausted_retry_budget_reports_zero_granted_retries(tiny_model):
    """request_retries() counts GRANTED requeues: a budget-0 shed was
    never retried, so the report's retried_requests and the fleet
    retries counter agree (both 0)."""
    clock = VirtualClock()
    cluster = _cluster(tiny_model, clock, n=2, retry_budget=0)
    rid = cluster.add_request(list(range(4)), max_new_tokens=4)
    cluster._meta[rid]["replica"] = None
    cluster._requeue(rid, clock.now(), {})
    out = cluster.outputs()[rid]
    assert out.status == "shed"
    assert out.finish_reason == "retries_exhausted"
    assert cluster.request_retries(rid) == 0
    assert cluster.counters["retries"] == 0
    assert cluster.counters["retry_budget_sheds"] == 1


def test_permanent_fleet_loss_sheds_structured_never_hangs(tiny_model):
    """Every replica DOWN with no recovery scheduled: parked requests
    (retry budget NOT exhausted) must convert to structured sheds —
    has_unfinished() goes False instead of spinning forever."""
    clock = VirtualClock()
    faults = FaultSchedule([
        FaultEvent(t=0.02, replica=0, kind="crash")])     # never recovers
    cluster = _cluster(tiny_model, clock, n=1, retry_budget=3,
                       faults=faults)
    rid = cluster.add_request(list(range(6)), max_new_tokens=8)
    for _ in range(50):
        clock.advance(0.01)
        cluster.step()
        if not cluster.has_unfinished():
            break
    out = cluster.outputs()[rid]
    assert out.status == "shed"
    assert out.finish_reason == "fleet_unavailable"
    assert cluster.counters["fleet_unavailable_sheds"] == 1
    assert not cluster.has_unfinished()


def test_overlapping_kv_pressure_windows_merge_and_extend(tiny_model):
    """A second kv_pressure event landing inside an open ballast window
    extends the pressure to the later end (and is counted) instead of
    being silently dropped."""
    clock = VirtualClock()
    faults = FaultSchedule([
        FaultEvent(t=0.01, replica=0, kind="kv_pressure", duration_s=0.05,
                   magnitude=0.5),
        FaultEvent(t=0.03, replica=0, kind="kv_pressure", duration_s=0.10,
                   magnitude=0.5)])
    cluster = _cluster(tiny_model, clock, n=1, faults=faults)
    rep = cluster.replicas[0]
    clock.advance(0.012)
    cluster.step()
    assert rep.ballast_id in rep.engine.pool
    first_until = rep.ballast_until
    clock.advance(0.02)                    # t=0.032: second event merges
    cluster.step()
    assert cluster.counters["kv_pressure_faults"] == 2
    assert rep.ballast_until == pytest.approx(0.032 + 0.10)
    assert rep.ballast_until > first_until
    clock.advance(0.04)                    # t=0.072: past the FIRST end
    cluster.step()
    assert rep.ballast_id in rep.engine.pool, "merged window still open"
    clock.advance(0.08)                    # t=0.152: past the merged end
    cluster.step()
    assert rep.ballast_id not in rep.engine.pool
